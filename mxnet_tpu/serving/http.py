"""Stdlib HTTP front end for :class:`~mxnet_tpu.serving.server.ModelServer`.

Dependency-free by design (the container bakes no web framework): a
``ThreadingHTTPServer`` whose per-connection threads block on serving
futures — the batcher, not the HTTP layer, is the concurrency control.

Endpoints:

* ``POST /v1/inference`` — body ``{"instances": [sample, ...]}`` (each
  sample a nested list matching the model's per-input sample shape; a
  multi-input model takes ``[[in0, in1, ...], ...]``) or the one-sample
  shorthand ``{"data": sample}``.  Optional ``"deadline_ms"``.  Replies
  ``{"predictions": [...]}``.  Overload -> **429** with the structured
  shed payload (reason, queue_depth, retry_after_ms) and a Retry-After
  header; malformed input -> 400; model fault -> 500.
* ``GET /metrics`` — Prometheus text from the process metrics registry
  (queue depth, batch sizes, shed counts, per-bucket compiles, ...).
* ``GET /healthz`` — liveness + queue/compile-cache snapshot.
* ``GET /v1/model`` — model + bucket-policy description.
"""
from __future__ import annotations

import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional, Tuple

import numpy as _np

from ..base import MXNetError
from .batching import OverloadError
from .server import DegradedError, ModelServer

__all__ = ["make_http_server"]

_MAX_BODY = 64 * 1024 * 1024


def _decode_samples(server: ModelServer, payload: Any
                    ) -> Tuple[List[Tuple[_np.ndarray, ...]],
                               Optional[float]]:
    if not isinstance(payload, dict):
        raise ValueError("body must be a JSON object")
    deadline_ms = payload.get("deadline_ms")
    if deadline_ms is not None and not isinstance(deadline_ms,
                                                  (int, float)):
        raise ValueError("deadline_ms must be a number")
    if "instances" in payload:
        raw = payload["instances"]
        if not isinstance(raw, list) or not raw:
            raise ValueError("'instances' must be a non-empty list")
    elif "data" in payload:
        raw = [payload["data"]]
    else:
        raise ValueError("body needs 'instances' or 'data'")
    sig = server.model.input_signature
    samples = []
    for inst in raw:
        parts = inst if len(sig) > 1 else [inst]
        if len(parts) != len(sig):
            raise ValueError(
                f"each instance must carry {len(sig)} inputs")
        samples.append(tuple(
            _np.asarray(p, dtype=d) for p, (_, d) in zip(parts, sig)))
    return samples, deadline_ms


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-serving/0.1"
    protocol_version = "HTTP/1.1"

    # the ModelServer rides on the HTTP server object (set in
    # make_http_server)
    @property
    def _ms(self) -> ModelServer:
        return self.server.model_server     # type: ignore[attr-defined]

    def log_message(self, fmt: str, *args: Any) -> None:
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _reply(self, code: int, body: Any,
               content_type: str = "application/json",
               headers: Optional[dict] = None) -> None:
        data = body if isinstance(body, bytes) else \
            json.dumps(body).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    # -- GET ---------------------------------------------------------------
    def do_GET(self) -> None:   # noqa: N802 - http.server API
        try:
            self._get()
        except Exception as e:   # noqa: BLE001 - handler must answer
            self._reply(500, {"error": "internal", "detail": str(e)})

    def _get(self) -> None:
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            from .. import metrics
            self._reply(200, metrics.render_text().encode(),
                        content_type="text/plain; version=0.0.4")
        elif path == "/healthz":
            d = self._ms.describe()
            if not self._ms.healthy():
                # dead worker thread: requests would queue forever —
                # tell the load balancer to stop sending traffic
                self._reply(503, {"status": "degraded",
                                  "detail": "serving worker thread has "
                                            "died; restart the server",
                                  "queue": d["queue"]})
            else:
                self._reply(200, {"status": "ok",
                                  "queue": d["queue"],
                                  "exec_cache": d["exec_cache"]})
        elif path == "/v1/model":
            self._reply(200, self._ms.describe())
        else:
            self._reply(404, {"error": "not_found", "path": path})

    # -- POST --------------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802 - http.server API
        try:
            self._post()
        except Exception as e:   # noqa: BLE001 - handler must answer
            self._reply(500, {"error": "internal", "detail": str(e)})

    def _post(self) -> None:
        path = self.path.split("?", 1)[0]
        if path not in ("/v1/inference", "/invocations"):
            self._reply(404, {"error": "not_found", "path": path})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                raise ValueError(f"bad Content-Length {length}")
            payload = json.loads(self.rfile.read(length))
            samples, deadline_ms = _decode_samples(self._ms, payload)
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            # TypeError covers valid-JSON-wrong-structure payloads
            # (null data, scalar instances, ...): still the caller's bug
            self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        futs: List[Any] = []

        def _abandon() -> None:
            # a partial failure abandons the sibling instances: cancel
            # them so the worker skips the wasted compute
            for f in futs:
                f.cancel()

        # submit phase: errors here are the CALLER's (shape/arity/
        # over-long length -> 400) or backpressure (-> 429)
        try:
            for s in samples:
                futs.append(self._ms.infer_async(
                    *s, deadline_ms=deadline_ms))
        except OverloadError as e:
            _abandon()
            self._reply(429, e.to_json(), headers={
                "Retry-After": str(max(1, int(e.retry_after_ms / 1e3)))})
            return
        except DegradedError as e:
            # server-side incapacity (dead worker / stopped), NOT the
            # caller's bug: 503 tells the balancer to fail over
            _abandon()
            self._reply(503, {"error": "degraded", "detail": str(e)},
                        headers={"Retry-After": "1"})
            return
        except MXNetError as e:
            _abandon()
            self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        # gather phase: deadline sheds are still 429; anything else is a
        # server-side fault (500)
        try:
            preds = []
            for f in futs:
                out = f.result(timeout=60.0)
                outs = out if isinstance(out, list) else [out]
                vals = [o.tolist() for o in outs]
                preds.append(vals[0] if len(vals) == 1 else vals)
        except OverloadError as e:
            _abandon()
            self._reply(429, e.to_json(), headers={
                "Retry-After": str(max(1, int(e.retry_after_ms / 1e3)))})
            return
        except Exception as e:   # noqa: BLE001 - request-scoped fault
            _abandon()
            self._reply(500, {"error": "inference_failed",
                              "detail": str(e)})
            return
        self._reply(200, {"predictions": preds})


def make_http_server(model_server: ModelServer, host: str = "127.0.0.1",
                     port: int = 8080,
                     verbose: bool = False) -> ThreadingHTTPServer:
    """Bind the HTTP front end (``port=0`` picks a free port; the bound
    address is ``httpd.server_address``).  Run with ``serve_forever()``;
    the caller owns ``model_server.start()/stop()``."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.model_server = model_server       # type: ignore[attr-defined]
    httpd.verbose = verbose                 # type: ignore[attr-defined]
    return httpd
