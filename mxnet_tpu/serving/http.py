"""Stdlib HTTP front end for :class:`~mxnet_tpu.serving.server.ModelServer`.

Dependency-free by design (the container bakes no web framework): a
``ThreadingHTTPServer`` whose per-connection threads block on serving
futures — the batcher, not the HTTP layer, is the concurrency control.

Endpoints:

* ``POST /v1/inference`` — body ``{"instances": [sample, ...]}`` (each
  sample a nested list matching the model's per-input sample shape; a
  multi-input model takes ``[[in0, in1, ...], ...]``) or the one-sample
  shorthand ``{"data": sample}``.  Optional ``"deadline_ms"``.  Replies
  ``{"predictions": [...]}``.  Overload -> **429** with the structured
  shed payload (reason, queue_depth, retry_after_ms) and a Retry-After
  header; malformed input -> 400; model fault -> 500.
* ``POST /v1/generate`` — body ``{"tokens": [id, ...]}`` (the prompt;
  ``"prompt"`` is an accepted alias) + optional ``"max_new_tokens"``,
  ``"eos_token"``, ``"deadline_ms"``, ``"stream"``, and the sampling
  controls ``"method"`` (``greedy`` | ``sample`` | ``top_k`` |
  ``top_p``), ``"temperature"`` (> 0), ``"top_k"`` (>= 1),
  ``"top_p"`` (in (0, 1]), ``"seed"`` (same seed => same stream, the
  determinism contract recovery relies on).  Out-of-range values ->
  **400** with the offending rule named, on the stream and collect
  paths alike.  Streaming (the
  default, ``MXNET_GEN_STREAM``) answers **chunked**: one NDJSON line
  per token (``{"token": id, "index": i}``) the moment the decode
  iteration produces it, then a ``{"done": true, ...}`` trailer line.
  ``"stream": false`` answers one JSON object after the sequence
  finishes.  No slot within the deadline / queue full -> **429** with
  the same structured shed payload; dead decode worker -> 503.
* ``GET /metrics`` — Prometheus text from the process metrics registry
  (queue depth, batch sizes, shed counts, per-bucket compiles, slot
  occupancy, tokens/sec, TTFT, recoveries, restarts, ...).
* ``GET /healthz`` (alias ``/readyz``) — **readiness**: 200 only while
  the process should receive NEW traffic; 503 when degraded (circuit
  breaker open / every worker replica dead) or draining (SIGTERM
  received).  Wire the load balancer here.
* ``GET /livez`` — **liveness**: 200 as long as the process answers,
  INCLUDING while draining or degraded.  Wire the orchestrator's
  restart probe here — killing a pod because its dependency broke, or
  mid-drain, would turn graceful restarts into outages.
* ``GET /v1/model`` — model + bucket-policy (+ generation engine)
  description.
"""
from __future__ import annotations

import json
import socket
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, List, Optional, Tuple

import numpy as _np

from .. import tracing as _tracing
from ..base import MXNetError
from .batching import OverloadError
from .generation import StreamTimeout
from .server import DegradedError, ModelServer

__all__ = ["make_http_server"]

_MAX_BODY = 64 * 1024 * 1024


def _decode_samples(server: ModelServer, payload: Any
                    ) -> Tuple[List[Tuple[_np.ndarray, ...]],
                               Optional[float]]:
    if not isinstance(payload, dict):
        raise ValueError("body must be a JSON object")
    deadline_ms = payload.get("deadline_ms")
    if deadline_ms is not None and not isinstance(deadline_ms,
                                                  (int, float)):
        raise ValueError("deadline_ms must be a number")
    if "instances" in payload:
        raw = payload["instances"]
        if not isinstance(raw, list) or not raw:
            raise ValueError("'instances' must be a non-empty list")
    elif "data" in payload:
        raw = [payload["data"]]
    else:
        raise ValueError("body needs 'instances' or 'data'")
    sig = server.model.input_signature
    samples = []
    for inst in raw:
        parts = inst if len(sig) > 1 else [inst]
        if len(parts) != len(sig):
            raise ValueError(
                f"each instance must carry {len(sig)} inputs")
        samples.append(tuple(
            _np.asarray(p, dtype=d) for p, (_, d) in zip(parts, sig)))
    return samples, deadline_ms


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-serving/0.1"
    protocol_version = "HTTP/1.1"

    # the ModelServer / GenerationServer ride on the HTTP server object
    # (set in make_http_server); either may be absent
    @property
    def _ms(self) -> Optional[ModelServer]:
        return self.server.model_server     # type: ignore[attr-defined]

    @property
    def _gs(self) -> Any:
        return getattr(self.server, "generation_server", None)

    def log_message(self, fmt: str, *args: Any) -> None:
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def _reply(self, code: int, body: Any,
               content_type: str = "application/json",
               headers: Optional[dict] = None) -> None:
        data = body if isinstance(body, bytes) else \
            json.dumps(body).encode()
        self.send_response(code)
        tp = _tracing.traceparent()
        if tp is not None:
            # echo the request's trace context so the caller can join
            # its client-side span to what GET /v1/traces will show
            self.send_header("traceparent", tp)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    # -- GET ---------------------------------------------------------------
    def do_GET(self) -> None:   # noqa: N802 - http.server API
        try:
            self._get()
        except Exception as e:   # noqa: BLE001 - handler must answer
            self._reply(500, {"error": "internal", "detail": str(e)})

    def _get(self) -> None:
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            from .. import metrics
            self._reply(200, metrics.render_text().encode(),
                        content_type="text/plain; version=0.0.4")
        elif path in ("/healthz", "/readyz"):
            draining = any(
                s is not None and getattr(s, "draining", False)
                for s in (self._ms, self._gs))
            degraded = []
            if self._ms is not None and not self._ms.healthy():
                degraded.append(
                    "serving worker replicas are not serving")
            if self._gs is not None and not self._gs.healthy():
                degraded.append(
                    "generation worker replicas are not serving")
            body: dict = {}
            if self._ms is not None:
                d = self._ms.describe()
                body["queue"] = d["queue"]
                body["exec_cache"] = d["exec_cache"]
                body["resilience"] = d["resilience"]
            if self._gs is not None:
                g = self._gs.describe()
                body["generation"] = {"slots": g["slots"],
                                      "queue": g["queue"],
                                      "resilience": g["resilience"]}
            if draining:
                # readiness drops out of rotation FIRST; resident work
                # is still finishing and liveness (/livez) stays 200
                body.pop("exec_cache", None)
                self._reply(503, dict(body, status="draining",
                                      detail="draining: admissions "
                                      "shed; resident work finishing"))
            elif degraded:
                # no serving capacity: requests would queue forever —
                # tell the load balancer to stop sending traffic
                body.pop("exec_cache", None)
                self._reply(503, dict(body, status="degraded",
                                      detail="; ".join(degraded)
                                      + "; reset the breaker or "
                                      "restart the server"))
            else:
                self._reply(200, dict(body, status="ok"))
        elif path == "/livez":
            # liveness: the process answers — even degraded or draining
            # (the orchestrator must NOT kill a draining pod)
            self._reply(200, {
                "status": "alive",
                "draining": any(
                    s is not None and getattr(s, "draining", False)
                    for s in (self._ms, self._gs)),
                "degraded": any(
                    s is not None and getattr(s, "degraded", False)
                    for s in (self._ms, self._gs)),
            })
        elif path == "/v1/traces":
            # the span ring buffer as Chrome/Perfetto trace-event JSON
            # (same shape the profiler dumps — one chrome://tracing
            # load shows both)
            self._reply(200, _tracing.export_trace_events())
        elif path == "/v1/model":
            out = (self._ms.describe() if self._ms is not None else {})
            if self._gs is not None:
                out["generation"] = self._gs.describe()
            self._reply(200, out)
        else:
            self._reply(404, {"error": "not_found", "path": path})

    # -- POST --------------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802 - http.server API
        try:
            # trace context: continue the caller's trace when the
            # request carries a W3C traceparent header, else start a
            # fresh (head-sampled) one.  Everything downstream —
            # batcher queue wait, prefill, token stream — parents
            # under this span.
            rctx = _tracing.parse_traceparent(
                self.headers.get("traceparent"))
            with _tracing.attach(rctx):
                with _tracing.span(
                        "http.request", method="POST",
                        path=self.path.split("?", 1)[0]):
                    self._post()
        except Exception as e:   # noqa: BLE001 - handler must answer
            self._reply(500, {"error": "internal", "detail": str(e)})

    def _post(self) -> None:
        path = self.path.split("?", 1)[0]
        if path == "/v1/generate":
            self._post_generate()
            return
        if path not in ("/v1/inference", "/invocations"):
            self._reply(404, {"error": "not_found", "path": path})
            return
        if self._ms is None:
            self._reply(404, {"error": "not_found", "path": path,
                              "detail": "this server hosts only "
                                        "/v1/generate"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                raise ValueError(f"bad Content-Length {length}")
            payload = json.loads(self.rfile.read(length))
            samples, deadline_ms = _decode_samples(self._ms, payload)
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            # TypeError covers valid-JSON-wrong-structure payloads
            # (null data, scalar instances, ...): still the caller's bug
            self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        futs: List[Any] = []

        def _abandon() -> None:
            # a partial failure abandons the sibling instances: cancel
            # them so the worker skips the wasted compute
            for f in futs:
                f.cancel()

        # submit phase: errors here are the CALLER's (shape/arity/
        # over-long length -> 400) or backpressure (-> 429)
        try:
            for s in samples:
                futs.append(self._ms.infer_async(
                    *s, deadline_ms=deadline_ms))
        except OverloadError as e:
            _abandon()
            self._reply(429, e.to_json(), headers={
                "Retry-After": str(max(1, int(e.retry_after_ms / 1e3)))})
            return
        except DegradedError as e:
            # server-side incapacity (dead worker / stopped), NOT the
            # caller's bug: 503 tells the balancer to fail over
            _abandon()
            self._reply(503, {"error": "degraded", "detail": str(e)},
                        headers={"Retry-After": "1"})
            return
        except MXNetError as e:
            _abandon()
            self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        # gather phase: deadline sheds are still 429; anything else is a
        # server-side fault (500)
        try:
            preds = []
            for f in futs:
                out = f.result(timeout=60.0)
                outs = out if isinstance(out, list) else [out]
                vals = [o.tolist() for o in outs]
                preds.append(vals[0] if len(vals) == 1 else vals)
        except OverloadError as e:
            _abandon()
            self._reply(429, e.to_json(), headers={
                "Retry-After": str(max(1, int(e.retry_after_ms / 1e3)))})
            return
        except Exception as e:   # noqa: BLE001 - request-scoped fault
            _abandon()
            self._reply(500, {"error": "inference_failed",
                              "detail": str(e)})
            return
        self._reply(200, {"predictions": preds})

    # -- generation (continuous batching, per-token streaming) -------------
    def _post_generate(self) -> None:
        from ..base import getenv
        gs = self._gs
        if gs is None:
            self._reply(404, {"error": "not_found",
                              "path": "/v1/generate",
                              "detail": "no generation engine is "
                                        "hosted (serve a decoder LM "
                                        "with --generate)"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            if length <= 0 or length > _MAX_BODY:
                raise ValueError(f"bad Content-Length {length}")
            payload = json.loads(self.rfile.read(length))
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
            toks = payload.get("tokens", payload.get("prompt"))
            if not isinstance(toks, list) or not toks or \
                    not all(isinstance(t, int) for t in toks):
                raise ValueError(
                    "'tokens' (or 'prompt') must be a non-empty list "
                    "of token ids")
            max_new = int(payload.get("max_new_tokens", 64))
            eos = payload.get("eos_token")
            if eos is not None:
                eos = int(eos)
            deadline_ms = payload.get("deadline_ms")
            if deadline_ms is not None and not isinstance(
                    deadline_ms, (int, float)):
                raise ValueError("deadline_ms must be a number")
            # sampling parameters: type errors are caught HERE (400);
            # range errors (top_k < 1, top_p outside (0,1], bad
            # method, temperature <= 0) raise MXNetError from the
            # engine's zoo-rule validation below — also 400, on both
            # the stream and collect paths (validation precedes any
            # token)
            method = payload.get("method")
            if method is not None and not isinstance(method, str):
                raise ValueError("method must be a string (greedy / "
                                 "sample / top_k / top_p)")
            temperature = payload.get("temperature")
            if temperature is not None and not isinstance(
                    temperature, (int, float)):
                raise ValueError("temperature must be a number")
            top_k = payload.get("top_k")
            if top_k is not None and not isinstance(top_k, int):
                raise ValueError("top_k must be an integer")
            top_p = payload.get("top_p")
            if top_p is not None and not isinstance(top_p,
                                                    (int, float)):
                raise ValueError("top_p must be a number")
            seed = payload.get("seed")
            if seed is not None and not isinstance(seed, int):
                raise ValueError("seed must be an integer")
            speculative = payload.get("speculative")
            if speculative is not None and not isinstance(
                    speculative, bool):
                raise ValueError("speculative must be a boolean")
            stream_mode = bool(payload.get(
                "stream", int(getenv("MXNET_GEN_STREAM", 1))))
        except (TypeError, ValueError, json.JSONDecodeError) as e:
            self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        # submit: backpressure -> 429, dead worker -> 503, a budget
        # that cannot fit the KV ceiling (or out-of-range sampling
        # params) -> 400 (the caller's bug)
        try:
            stream = gs.generate(toks, max_new_tokens=max_new,
                                 eos_token=eos,
                                 deadline_ms=deadline_ms,
                                 method=method, temperature=temperature,
                                 top_k=top_k, top_p=top_p, seed=seed,
                                 speculative=speculative)
        except OverloadError as e:
            self._reply(429, e.to_json(), headers={
                "Retry-After": str(max(1, int(e.retry_after_ms / 1e3)))})
            return
        except DegradedError as e:
            self._reply(503, {"error": "degraded", "detail": str(e)},
                        headers={"Retry-After": "1"})
            return
        except MXNetError as e:
            self._reply(400, {"error": "bad_request", "detail": str(e)})
            return
        if not stream_mode:
            try:
                tokens = stream.result(timeout=300.0)
            except OverloadError as e:
                # no slot freed within the deadline: still a shed
                self._reply(429, e.to_json(), headers={
                    "Retry-After": str(max(1, int(e.retry_after_ms
                                                  / 1e3)))})
                return
            except Exception as e:   # noqa: BLE001 - request-scoped
                self._reply(500, {"error": "generation_failed",
                                  "detail": str(e)})
                return
            self._reply(200, {"tokens": tokens,
                              "finish_reason": stream.finish_reason})
            return
        self._stream_tokens(stream)

    def _client_gone(self) -> bool:
        """Peek the connection without consuming: a readable socket
        that yields EOF means the client hung up while its request was
        still queued."""
        import select
        try:
            r, _, _ = select.select([self.connection], [], [], 0)
            if r:
                return self.connection.recv(1, socket.MSG_PEEK) == b""
        except (OSError, ValueError):
            return True
        return False

    def _stream_tokens(self, stream: Any) -> None:
        """Chunked NDJSON: one line per token AS the decode loop emits
        it, then a done trailer.  The status line is DEFERRED until the
        first token exists: every shed (queue_full at submit, deadline
        at the admission boundary) happens strictly before any token is
        produced, so waiting for token #1 preserves the documented
        429/500 contract for streaming requests.  A failure after that
        becomes an error line on the already-committed 200 (the nature
        of streaming); a client disconnect cancels the sequence so its
        slot frees at the next iteration.

        The first-token wait POLLS for disconnects: a client that hangs
        up while its request is still in the prefill queue is evicted
        immediately (the queue budget frees NOW), so a flood of
        abandoned requests cannot hold queue_full sheds high."""
        deadline = time.monotonic() + 300.0
        with _tracing.child_span("stream.first_token"):
            while True:
                try:
                    first = stream.next_token(timeout=0.25)
                    break
                except StreamTimeout:
                    if self._client_gone():
                        stream.cancel()  # evicts a queued request NOW
                        return
                    if time.monotonic() >= deadline:
                        self._reply(500, {
                            "error": "generation_failed",
                            "detail": "timed out waiting for the "
                                      "first token"})
                        return
                except OverloadError as e:
                    # no slot freed within the deadline — still a 429
                    self._reply(429, e.to_json(), headers={
                        "Retry-After": str(max(
                            1, int(e.retry_after_ms / 1e3)))})
                    return
                except Exception as e:  # noqa: BLE001 - request-scoped
                    self._reply(500, {"error": "generation_failed",
                                      "detail": str(e)})
                    return
        if first is None:        # closed with zero tokens (shutdown)
            self._reply(500, {"error": "generation_failed",
                              "detail": "sequence closed before its "
                                        "first token"})
            return
        self.send_response(200)
        tp = _tracing.traceparent()
        if tp is not None:
            self.send_header("traceparent", tp)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-store")
        self.end_headers()

        def flush(lines: List[Any]) -> None:
            # ONE chunk may carry many NDJSON lines: a speculative
            # iteration lands its whole accepted run in one
            # TokenStream wakeup, and it leaves the socket as one
            # write too — per-token writes would hand the speculation
            # win straight back to syscall overhead
            if not lines:
                return
            data = b"".join((json.dumps(o) + "\n").encode()
                            for o in lines)
            self.wfile.write(f"{len(data):X}\r\n".encode() + data
                             + b"\r\n")
            self.wfile.flush()

        i = 0
        with _tracing.child_span("stream.completion") as csp:
            try:
                try:
                    pend = [{"token": int(first), "index": i}]
                    i += 1
                    done = False
                    while not done:
                        # batch everything already buffered behind the
                        # token in hand, flush once, then block for
                        # the next iteration's output
                        try:
                            while True:
                                tok = stream.next_token(timeout=0.0)
                                if tok is None:
                                    done = True
                                    break
                                pend.append({"token": int(tok),
                                             "index": i})
                                i += 1
                        except StreamTimeout:
                            pass             # drained; stream still live
                        flush(pend)
                        pend = []
                        if done:
                            break
                        tok = stream.next_token()
                        if tok is None:
                            done = True
                        else:
                            pend.append({"token": int(tok), "index": i})
                            i += 1
                except MXNetError as e:
                    flush([{"error": "generation_failed",
                            "detail": str(e), "done": True}])
                    self.wfile.write(b"0\r\n\r\n")
                    return
                flush([{"done": True, "n_tokens": i,
                        "finish_reason": stream.finish_reason}])
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                stream.cancel()
            finally:
                csp.set_attr(n_tokens=i,
                             finish_reason=stream.finish_reason)


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """Client hangups (reset/broken pipe mid-request) are ROUTINE for
    a streaming server under chaos or drain — swallow them instead of
    printing a traceback per abandoned connection; everything else
    still reports."""

    def handle_error(self, request: Any, client_address: Any) -> None:
        import sys as _sys
        exc = _sys.exc_info()[1]
        if isinstance(exc, (ConnectionResetError, BrokenPipeError,
                            ConnectionAbortedError, TimeoutError)):
            return
        super().handle_error(request, client_address)


def make_http_server(model_server: Optional[ModelServer],
                     host: str = "127.0.0.1",
                     port: int = 8080,
                     verbose: bool = False,
                     generation_server: Any = None
                     ) -> ThreadingHTTPServer:
    """Bind the HTTP front end (``port=0`` picks a free port; the bound
    address is ``httpd.server_address``).  Run with ``serve_forever()``;
    the caller owns the model/generation servers' ``start()/stop()``.
    Either server may be omitted; its endpoints then answer 404."""
    if model_server is None and generation_server is None:
        raise MXNetError("make_http_server needs a ModelServer and/or "
                         "a GenerationServer")
    httpd = _QuietThreadingHTTPServer((host, port), _Handler)
    httpd.daemon_threads = True
    httpd.model_server = model_server       # type: ignore[attr-defined]
    httpd.generation_server = generation_server  # type: ignore[attr-defined]
    httpd.verbose = verbose                 # type: ignore[attr-defined]
    return httpd
