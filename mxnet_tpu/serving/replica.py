"""Replica supervision — the serving layer's restart policy.

A production serving process treats a dead worker the way PR 3 taught
the training stack to treat a dead peer: as a routine, *bounded* event.
:class:`ReplicaSupervisor` owns that policy for both servers
(:class:`~mxnet_tpu.serving.server.ModelServer` and
:class:`~mxnet_tpu.serving.server.GenerationServer`):

* a dead worker replica is **restarted** after a jittered exponential
  backoff (the same :func:`mxnet_tpu.retry.backoff_delays` schedule the
  dist_async client uses — a fleet of replicas crashing on the same
  poisoned input must not restart in lockstep);
* each replica carries a **restart budget**
  (``MXNET_SERVING_MAX_RESTARTS``): past it the replica's circuit
  breaker trips and it leaves the rotation for good — a crash-looping
  worker burns CPU, floods logs, and churns every queued request, so
  explicit degradation beats optimistic retry number N+1;
* when **no** replica remains in rotation (alive, restarting, or
  waiting), the supervisor reports the server degraded: submits fail
  fast with a structured ``DegradedError`` and readiness goes 503 while
  liveness stays 200 — the load balancer routes away, the orchestrator
  does NOT kill the pod for a dependency fault;
* a **manual reset** (``reset()`` — surfaced as the servers'
  ``reset_breaker()``) refills every budget and re-admits traffic,
  the operator acknowledging the underlying cause is gone.

The supervisor is policy only: the owning server supplies ``spawn``
(bring replica ``rid`` back) and ``on_degraded`` (no rotation left).
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Iterator, Optional

from ..base import getenv, register_env
from .. import metrics as _metrics
from ..retry import backoff_delays

__all__ = ["ReplicaSupervisor"]

register_env(
    "MXNET_SERVING_REPLICAS", 1,
    "Worker replicas hosted by each serving server (ModelServer worker "
    "threads draining the shared batcher; GenerationServer decode "
    "engines behind the admission router). A dead replica's requests "
    "requeue to the survivors while the supervisor restarts it.")
register_env(
    "MXNET_SERVING_DRAIN_DEADLINE_S", 30,
    "Graceful-drain budget: on the first SIGTERM/SIGINT a serving "
    "process stops admissions (429 draining), finishes resident "
    "requests for at most this long, then stops. Readiness reports 503 "
    "for the whole window; liveness stays 200.")
register_env(
    "MXNET_SERVING_MAX_RESTARTS", 3,
    "Restart budget per serving worker replica: past this many "
    "restarts the replica's circuit breaker trips and it leaves the "
    "rotation (no more restart churn); when no replica remains the "
    "server degrades explicitly (DegradedError / readiness 503). "
    "reset_breaker() refills the budget.")
register_env(
    "MXNET_SERVING_RESTART_BACKOFF_MS", 100,
    "First-restart backoff after a serving worker replica dies; "
    "doubles per restart (jittered, shared schedule with "
    "MXNET_RETRY_* via retry.backoff_delays).")

WORKER_RESTARTS = _metrics.counter(
    "mxnet_serving_worker_restarts_total",
    "Serving worker replicas restarted by the replica supervisor after "
    "a worker death, by server kind (oneshot = ModelServer, generation "
    "= GenerationServer).", labels=("server",))
BREAKER_OPEN = _metrics.gauge(
    "mxnet_serving_breaker_open",
    "1 while a serving server's circuit breaker is open (every replica "
    "exhausted its MXNET_SERVING_MAX_RESTARTS budget — the server is "
    "degraded and sheds with DegradedError until reset_breaker()), by "
    "server kind.", labels=("server",))


class _ReplicaState:
    __slots__ = ("delays", "pending", "tripped")

    def __init__(self, delays: Iterator[float]) -> None:
        self.delays = delays
        self.pending = False      # a restart is scheduled/backing off
        self.tripped = False      # budget exhausted: out of rotation


class ReplicaSupervisor:
    """Restart/breaker policy for one server's replica set.

    ``spawn(rid)`` is called (from a supervisor-owned thread, after the
    backoff sleep) to bring a replica back; ``on_degraded(exc)`` fires
    exactly once when the last replica leaves the rotation.  The server
    reports ``alive_fn(rid) -> bool`` so rotation checks see reality,
    not bookkeeping.
    """

    def __init__(self, server_label: str, n_replicas: int,
                 spawn: Callable[[int], None],
                 on_degraded: Callable[[BaseException], None],
                 alive_fn: Callable[[int], bool],
                 max_restarts: Optional[int] = None,
                 backoff_ms: Optional[float] = None) -> None:
        self.label = server_label
        if max_restarts is None:
            max_restarts = int(getenv("MXNET_SERVING_MAX_RESTARTS", 3))
        if backoff_ms is None:
            backoff_ms = float(
                getenv("MXNET_SERVING_RESTART_BACKOFF_MS", 100))
        self.max_restarts = int(max_restarts)
        self.backoff_ms = float(backoff_ms)
        self._spawn = spawn
        self._on_degraded = on_degraded
        self._alive = alive_fn
        self._lock = threading.Lock()
        self._stopped = False
        self._degraded = False
        self._state: Dict[int, _ReplicaState] = {
            rid: _ReplicaState(self._fresh_delays())
            for rid in range(int(n_replicas))}
        BREAKER_OPEN.labels(server=self.label).set(0)

    def _fresh_delays(self) -> Iterator[float]:
        # max_restarts restarts => max_restarts backoff sleeps
        return backoff_delays(attempts=self.max_restarts + 1,
                              base_ms=self.backoff_ms)

    # -- state queries -------------------------------------------------------
    @property
    def degraded(self) -> bool:
        return self._degraded

    def restart_pending(self, rid: int) -> bool:
        with self._lock:
            st = self._state.get(rid)
            return bool(st and st.pending)

    def any_pending(self) -> bool:
        with self._lock:
            return any(st.pending for st in self._state.values())

    def tripped(self, rid: int) -> bool:
        with self._lock:
            st = self._state.get(rid)
            return bool(st and st.tripped)

    def in_rotation(self) -> int:
        """Replicas still serving or coming back: alive, or restart
        pending. Tripped replicas are out until reset()."""
        with self._lock:
            return sum(1 for rid, st in self._state.items()
                       if st.pending or (not st.tripped
                                         and self._alive(rid)))

    # -- the death event -----------------------------------------------------
    def notify_death(self, rid: int, exc: BaseException) -> bool:
        """A replica's worker died.  Returns True when a restart was
        scheduled; False when the replica's breaker tripped (and, if it
        was the last one in rotation, after ``on_degraded`` ran)."""
        with self._lock:
            if self._stopped or self._degraded:
                return False
            st = self._state[rid]
            delay = next(st.delays, None)
            if delay is None:
                st.tripped = True
                st.pending = False
                last = not any(
                    s.pending or (not s.tripped and self._alive(r))
                    for r, s in self._state.items())
                if last:
                    self._degraded = True
            else:
                st.pending = True
        if delay is None:
            if self._degraded:
                BREAKER_OPEN.labels(server=self.label).set(1)
                self._on_degraded(exc)
            return False
        t = threading.Thread(
            target=self._restart_after, args=(rid, delay),
            name=f"mxnet-serving-restart-{self.label}-{rid}",
            daemon=True)
        t.start()
        return True

    def _restart_after(self, rid: int, delay: float) -> None:
        import time
        time.sleep(delay)
        with self._lock:
            st = self._state.get(rid)
            if self._stopped or self._degraded or st is None \
                    or not st.pending:
                return
            st.pending = False
        WORKER_RESTARTS.labels(server=self.label).inc()
        try:
            # a restart is its own trace root: no request context
            # survives to the supervisor thread, but the span still
            # lands in the ring (error/slow restarts tail-upgrade)
            from .. import tracing as _tracing
            with _tracing.span("replica.restart", server=self.label,
                               replica=rid, delay_s=delay):
                self._spawn(rid)
        except Exception as e:   # noqa: BLE001 - a failed respawn is
            # one more death: spend another unit of the budget
            self.notify_death(rid, e)

    # -- operator controls ---------------------------------------------------
    def reset(self) -> None:
        """Refill every replica's restart budget and clear the breaker
        (the servers' ``reset_breaker()``).  The server re-spawns dead
        replicas itself after calling this."""
        with self._lock:
            self._degraded = False
            for st in self._state.values():
                st.delays = self._fresh_delays()
                st.pending = False
                st.tripped = False
        BREAKER_OPEN.labels(server=self.label).set(0)

    def stop(self) -> None:
        """Server shutdown: cancel pending restarts."""
        with self._lock:
            self._stopped = True
            for st in self._state.values():
                st.pending = False

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "max_restarts": self.max_restarts,
                "backoff_ms": self.backoff_ms,
                "degraded": self._degraded,
                "replicas": {
                    rid: {"alive": self._alive(rid),
                          "restart_pending": st.pending,
                          "breaker_tripped": st.tripped}
                    for rid, st in self._state.items()},
            }
