"""Continuous-batching generation engine: resident decode loop,
slot-based KV cache, per-token streaming.

PR 2's serving core batches INDEPENDENT one-shot forward passes; the
largest production traffic class — autoregressive LLM generation — is a
different shape entirely: each request is a long-lived *sequence* whose
per-token cost is tiny but whose lifetime spans thousands of model
invocations.  Batching at request granularity (wait for a full batch of
prompts, decode them lock-step to completion) wastes the machine twice:
short sequences pad out to the longest one, and new arrivals wait for
the whole batch to drain.

This engine implements **iteration-level scheduling** (the Orca /
vLLM-style discipline, via the Gemma-on-TPU serving comparison in
PAPERS.md) on top of the pieces PRs 2-5 built:

* the **decode inner loop is ONE compiled, shape-stable program**
  (`DecodeModel.step`) over every slot of a
  :class:`~mxnet_tpu.serving.kv_cache.PagedKVCache` — compiled once per
  KV capacity bucket and resident across requests (the Julia->TPU
  full-compilation lesson: never re-trace the hot loop);
* **admission happens BETWEEN decode iterations**: prefill (a separate
  per-prompt-bucket program) runs for the newcomers, their KV rows are
  written into free slots, and the very next iteration decodes old and
  new sequences together — no resident sequence ever stalls or changes
  its tokens because of an arrival;
* **retirement is per-step**: a sequence that emits EOS or reaches its
  max-tokens budget frees its slot at the END of that iteration, and
  the slot is admissible on the next one;
* **tokens stream out as they exist**: each iteration's (S,) token
  readback is pushed into per-request :class:`TokenStream` queues the
  HTTP layer drains as chunked responses.

Overload keeps PR-2 semantics: the admission queue is bounded
(queue_full shed at submit) and a request that cannot get a slot within
its deadline sheds with the same structured
:class:`~mxnet_tpu.serving.batching.OverloadError` the one-shot path
raises.  Faults at the PR-3 ``serving.execute`` site fail only the
sequences in flight at that iteration; the engine survives and keeps
serving.  Each iteration runs under the PR-5 hang watchdog.
"""
from __future__ import annotations

import collections
import itertools as _itertools
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from ..base import MXNetError, getenv, register_env
from .. import metrics as _metrics
from .. import tracing as _tracing
from .batching import REQUESTS_TOTAL, SlotScheduler
from .kv_cache import (PagedKVCache, PrefixCache, prefix_key,
                       round_up_bucket, _shrink_rows)
from .model import DecodeModel, METHOD_CODES

__all__ = ["GenerationEngine", "GenRequest", "StreamTimeout",
           "TokenStream", "make_recovery_request"]

register_env("MXNET_GEN_MAX_SLOTS", 8,
             "Decode slots in the generation engine: the number of "
             "sequences decoded concurrently by the resident "
             "continuous-batching step (the KV cache allocates this "
             "many rows).")
register_env("MXNET_GEN_MAX_TOKENS", 256,
             "Server-side cap on new tokens per generation request "
             "(a request asking for more is clamped; 0 disables the "
             "cap). Bounds slot hold time, which bounds admission "
             "latency under load.")
register_env("MXNET_GEN_STREAM", 1,
             "Default for per-token HTTP streaming on /v1/generate: 1 "
             "streams each token as a chunk the moment the decode "
             "iteration produces it; 0 answers with the full "
             "completion. Per-request 'stream' overrides.")
register_env("MXNET_GEN_METHOD", "greedy",
             "Default decode method for generation requests that name "
             "none: greedy | sample | top_k | top_p. Sampling runs "
             "inside the compiled decode step (per-slot counter-PRNG "
             "keys), so the method never changes the readback shape "
             "or recompiles.")
register_env("MXNET_GEN_TEMPERATURE", 1.0,
             "Default sampling temperature for generation requests "
             "that name none (must be > 0; greedy ignores it). "
             "Per-request 'temperature' overrides.")
register_env("MXNET_GEN_TOP_K", 40,
             "Default k for top_k decoding when the request names "
             "none (>= 1, clamped to the vocab size). Per-request "
             "'top_k' overrides.")
register_env("MXNET_GEN_TOP_P", 0.9,
             "Default nucleus mass for top_p decoding when the "
             "request names none (0 < top_p <= 1). Per-request "
             "'top_p' overrides.")
register_env("MXNET_GEN_SPEC_MODE", "off",
             "Speculative decoding mode for the generation engine: "
             "'off' (one token per slot per iteration), 'self' (the "
             "target's own bottom MXNET_GEN_SPEC_DRAFT_LAYERS layers "
             "draft), or 'draft' (a separate small model passed to "
             "the engine as draft_model= drafts). Output is "
             "byte-identical to 'off' at the same seed — speculation "
             "only changes how many tokens an iteration emits. "
             "Per-request 'speculative': false opts a request out.")
register_env("MXNET_GEN_SPEC_K", 4,
             "Draft tokens proposed per slot per iteration when "
             "speculative decoding is on (>= 1). The target verifies "
             "k proposals plus its own next token in one pass, so an "
             "iteration emits 1..k+1 tokens per speculative slot.")
register_env("MXNET_GEN_SPEC_DRAFT_LAYERS", 0,
             "Transformer layers the self-speculative draft keeps "
             "from the target model (spec mode 'self'; 0 = half the "
             "target's layers). Fewer layers = cheaper proposals but "
             "lower acceptance.")


class StreamTimeout(MXNetError):
    """``TokenStream.next_token`` gave up waiting (NOT a request
    failure: the sequence may still produce — the HTTP layer uses short
    timeouts to poll for client disconnects while queued)."""


class TokenStream:
    """Per-request token channel: the engine produces, exactly one
    consumer (HTTP handler or in-process caller) drains.

    Iterate for per-token streaming (``for tok in stream``), or call
    :meth:`result` for collect-all.  A failed request raises its error
    from whichever call observes it (structured ``OverloadError`` for
    sheds — HTTP maps those to 429 even mid-stream-setup).

    The stream is the **exactly-once boundary** for recovery: every
    producer-side :meth:`put` carries the token's absolute index, and
    an index the transcript already holds is dropped (a resurrected
    producer replaying the join point), while an index PAST the
    transcript fails the stream loudly (a gap would silently corrupt
    the completion).  Consumers therefore see each index exactly once,
    in order, across any number of worker deaths."""

    def __init__(self) -> None:
        self._buf: Deque[Any] = collections.deque()
        self._lock = threading.Lock()
        self._ready = threading.Condition(self._lock)
        self._done = False
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self.finish_reason: Optional[str] = None
        self.tokens: List[int] = []     # producer-side transcript
        # notified on consumer cancel (the scheduler hooks this to
        # evict still-queued requests and free queue budget immediately)
        self._on_cancel: Optional[Any] = None

    # -- producer (engine) --------------------------------------------------
    def put(self, token: int, index: Optional[int] = None) -> None:
        gap: Optional[int] = None
        with self._lock:
            if self._done:
                return
            if index is not None:
                if index < len(self.tokens):
                    # duplicate from a recovered producer: the dedupe
                    # guard earns its keep
                    _metrics.SERVING_STREAM_DUPES_DROPPED.inc()
                    return
                if index > len(self.tokens):
                    gap = index
            if gap is None:
                self.tokens.append(int(token))
                self._buf.append(int(token))
                self._ready.notify_all()
        if gap is not None:
            # outside the lock: fail() retakes it
            self.fail(MXNetError(
                f"token stream gap: producer emitted index {gap} but "
                f"the transcript holds {len(self.tokens)} tokens — a "
                "recovery dropped tokens (exactly-once invariant "
                "violated)"))

    def put_many(self, tokens: Sequence[int], start_index: int) -> None:
        """Append a CONTIGUOUS run of tokens whose first absolute index
        is ``start_index`` — the speculative path's multi-token
        emission.  Per-token semantics are identical to calling
        :meth:`put` in a loop (an index the transcript holds is
        dropped, an index past it fails the stream), but the whole run
        lands under ONE lock pass with one consumer wakeup, so the
        HTTP layer drains it as one chunked write instead of k."""
        gap: Optional[int] = None
        with self._lock:
            if self._done:
                return
            for i, token in enumerate(tokens):
                index = int(start_index) + i
                if index < len(self.tokens):
                    _metrics.SERVING_STREAM_DUPES_DROPPED.inc()
                    continue
                if index > len(self.tokens):
                    gap = index
                    break
                self.tokens.append(int(token))
                self._buf.append(int(token))
            self._ready.notify_all()
        if gap is not None:
            self.fail(MXNetError(
                f"token stream gap: producer emitted index {gap} but "
                f"the transcript holds {len(self.tokens)} tokens — a "
                "recovery dropped tokens (exactly-once invariant "
                "violated)"))

    def close(self, finish_reason: str) -> None:
        with self._lock:
            if self._done:
                return
            self.finish_reason = finish_reason
            self._done = True
            self._ready.notify_all()

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            if self._done:
                return
            self._error = exc
            self.finish_reason = "error"
            self._done = True
            self._ready.notify_all()

    # -- consumer -----------------------------------------------------------
    def cancel(self) -> None:
        """Consumer gave up (client disconnect): a still-queued request
        is evicted immediately (freeing queue budget); a slot-resident
        sequence retires at the next iteration boundary."""
        with self._lock:
            already = self._cancelled or self._done
            self._cancelled = True
            self._done = True
            self._ready.notify_all()
            cb = self._on_cancel
        if cb is not None and not already:
            try:
                cb()
            except Exception:   # noqa: BLE001 - eviction is advisory
                pass

    def is_cancelled(self) -> bool:
        with self._lock:
            return self._cancelled

    @property
    def finished(self) -> bool:
        """Producer-side: the engine closed/failed/cancelled this
        sequence (tokens may still be buffered for the consumer)."""
        with self._lock:
            return self._done

    @property
    def done(self) -> bool:
        """Consumer-side: finished AND fully drained."""
        with self._lock:
            return self._done and not self._buf

    def next_token(self, timeout: float = 60.0) -> Any:
        """The next streamed token, or ``None`` at end-of-stream;
        raises the request's error if it failed."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while True:
                if self._buf:
                    return self._buf.popleft()
                if self._done:
                    if self._error is not None:
                        raise self._error
                    return None
                left = deadline - time.monotonic()
                if left <= 0:
                    raise StreamTimeout(
                        "timed out waiting for the next generated "
                        f"token ({timeout}s)")
                self._ready.wait(left)

    def __iter__(self):
        while True:
            t = self.next_token()
            if t is None:
                return
            yield t

    def result(self, timeout: float = 120.0) -> List[int]:
        """Block until the sequence finishes; returns all tokens."""
        deadline = time.monotonic() + timeout
        out: List[int] = []
        while True:
            t = self.next_token(timeout=max(0.001,
                                            deadline - time.monotonic()))
            if t is None:
                return out
            out.append(t)


class GenRequest:
    """One generation request riding the scheduler: prompt, budget,
    stream, timing/slot bookkeeping.

    Recovery reincarnates a request as a NEW ``GenRequest`` carrying
    the SAME :class:`TokenStream`: ``tokens`` becomes the original
    prompt plus every token already emitted, ``max_new_tokens`` the
    remaining budget, and ``offset`` the absolute index of the next
    token — decode is deterministic (greedy by definition; sampling by
    seed: token ``i`` draws under ``fold_in(PRNGKey(seed), i)`` no
    matter which program emits it), so the resurrected sequence is
    token-identical to a fault-free run and the stream's index dedupe
    makes the join exactly-once.  ``orig_prompt`` and
    ``total_new_tokens`` stay absolute so a second death recovers from
    the stream transcript again."""

    __slots__ = ("tokens", "max_new_tokens", "eos_token", "stream",
                 "enqueue_t", "deadline_t", "slot", "emitted",
                 "t_first", "request_id", "orig_prompt",
                 "total_new_tokens", "offset", "recover_t0",
                 "recoveries", "method", "temperature", "top_k",
                 "top_p", "seed", "speculative", "trace")

    _SEQ = _itertools.count(1)

    def __init__(self, tokens: _np.ndarray, max_new_tokens: int,
                 eos_token: Optional[int],
                 deadline_t: Optional[float],
                 stream: Optional[TokenStream] = None,
                 orig_prompt: Optional[_np.ndarray] = None,
                 total_new_tokens: Optional[int] = None,
                 offset: int = 0,
                 method: str = "greedy",
                 temperature: float = 1.0,
                 top_k: int = 40,
                 top_p: float = 0.9,
                 seed: int = 0,
                 speculative: bool = False) -> None:
        self.tokens = tokens
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token = eos_token
        self.method = str(method)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.speculative = bool(speculative)
        self.stream = stream if stream is not None else TokenStream()
        self.enqueue_t = time.monotonic()
        self.deadline_t = deadline_t
        self.slot: Optional[int] = None
        self.emitted = 0
        self.t_first: Optional[float] = None
        self.request_id = next(GenRequest._SEQ)
        self.orig_prompt = orig_prompt if orig_prompt is not None \
            else tokens
        self.total_new_tokens = int(
            total_new_tokens if total_new_tokens is not None
            else max_new_tokens)
        self.offset = int(offset)
        self.recover_t0: Optional[float] = None
        self.recoveries = 0     # resurrections so far (budgeted by the
        #                         server against restart churn)
        # trace context captured at submit; the engine thread attaches
        # it so queue-wait/prefill spans land in the request's trace
        self.trace = _tracing.capture()

    # scheduler duck-type
    def fail(self, exc: BaseException) -> None:
        self.stream.fail(exc)

    def is_cancelled(self) -> bool:
        return self.stream.is_cancelled()


def make_recovery_request(req: GenRequest) -> GenRequest:
    """Reincarnate ``req`` at its stream's current transcript: the
    resubmitted prompt is ``original prompt + tokens already emitted``
    (deterministic decode continues exactly where the dead worker left
    off — greedy trivially, sampling by replaying the request's
    counter-key stream from ``seed`` at the emitted-token offset), the
    budget is what remains, and the SAME stream rides along with its
    index offset advanced.  No deadline: the request was already
    admitted once — shedding it now would drop an accepted stream."""
    emitted = len(req.stream.tokens)
    if emitted:
        prompt = _np.concatenate(
            [_np.asarray(req.orig_prompt, _np.int32),
             _np.asarray(req.stream.tokens, _np.int32)])
    else:
        prompt = _np.asarray(req.orig_prompt, _np.int32)
    remaining = req.total_new_tokens - emitted
    if remaining < 1:
        raise MXNetError(
            f"request {req.request_id} has no remaining budget "
            f"({emitted}/{req.total_new_tokens} emitted) — it should "
            "have been closed, not recovered")
    r = GenRequest(prompt, remaining, req.eos_token, None,
                   stream=req.stream, orig_prompt=req.orig_prompt,
                   total_new_tokens=req.total_new_tokens,
                   offset=emitted, method=req.method,
                   temperature=req.temperature, top_k=req.top_k,
                   top_p=req.top_p, seed=req.seed,
                   speculative=req.speculative)
    r.recover_t0 = time.monotonic()
    r.recoveries = req.recoveries + 1
    r.trace = req.trace      # the resurrection stays in the original
    #                          request's trace (recovery spans included)
    return r


class GenerationEngine:
    """The resident decode loop over a slot table.

    Drive it from one owner thread (``ModelServer``'s generation worker
    in production, the test directly otherwise)::

        eng = GenerationEngine(DecodeModel.from_block(gpt))
        eng.warmup()
        stream = eng.submit(prompt_ids, max_new_tokens=32)
        while eng.run_iteration():   # or let GenerationServer loop
            pass
        print(stream.result())

    ``run_iteration`` is ONE scheduling quantum: retire finished
    sequences, admit newcomers into freed slots (prefill), then execute
    one decode step over every active slot.  Everything the iteration
    does is recorded in :attr:`iteration_log` (bounded ring) — the
    continuous-batching invariant ("admission changes no resident
    sequence's tokens") is asserted against these per-iteration slot
    logs in CI.
    """

    LOG_KEEP = 4096

    def __init__(self, model: DecodeModel,
                 max_slots: Optional[int] = None,
                 kv_buckets: Optional[Sequence[int]] = None,
                 queue_limit: Optional[int] = None,
                 max_tokens: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 prefix_slots: Optional[int] = None,
                 prefix_cache: Optional[PrefixCache] = None,
                 default_method: Optional[str] = None,
                 default_temperature: Optional[float] = None,
                 default_top_k: Optional[int] = None,
                 default_top_p: Optional[float] = None,
                 spec_mode: Optional[str] = None,
                 spec_k: Optional[int] = None,
                 spec_draft_layers: Optional[int] = None,
                 draft_model: Any = None) -> None:
        self.model = model
        if max_slots is None:
            max_slots = int(getenv("MXNET_GEN_MAX_SLOTS", 8))
        self.max_slots = int(max_slots)
        # server-side sampling defaults (per-request values override);
        # validated HERE so a bad env/CLI default fails at startup,
        # not per-request
        self.default_method = str(
            default_method if default_method is not None
            else getenv("MXNET_GEN_METHOD", "greedy"))
        self.default_temperature = float(
            default_temperature if default_temperature is not None
            else getenv("MXNET_GEN_TEMPERATURE", 1.0))
        self.default_top_k = int(
            default_top_k if default_top_k is not None
            else getenv("MXNET_GEN_TOP_K", 40))
        self.default_top_p = float(
            default_top_p if default_top_p is not None
            else getenv("MXNET_GEN_TOP_P", 0.9))
        self._validate_sampling(self.default_method,
                                self.default_temperature,
                                self.default_top_k,
                                self.default_top_p, seed=0)
        # the position table bounds everything: a position past
        # max_length would silently clamp-gather the embedding, so the
        # cache only ever allocates buckets the model can address
        from .kv_cache import kv_bucket_grid
        full = kv_bucket_grid(kv_buckets)
        self.grid = tuple(b for b in full if b <= model.max_length)
        if not self.grid:
            raise MXNetError(
                f"no KV bucket <= model max_length {model.max_length} "
                f"(grid {full})")
        self.cache = PagedKVCache(
            model.n_layers, model.num_heads, model.head_dim,
            self.max_slots, buckets=self.grid, dtype=model.dtype,
            prefix=prefix_cache, prefix_slots=prefix_slots)
        # prompt pad grid: powers of two up to the top usable bucket —
        # mixed prompt lengths land on a handful of prefill programs
        top = self.grid[-1]
        pb, b = [], 8
        while b < top:
            pb.append(b)
            b *= 2
        pb.append(top)
        self.prompt_buckets = tuple(sorted(set(pb)))
        self.scheduler = SlotScheduler(self.max_slots,
                                       queue_limit=queue_limit)
        self.max_tokens_cap = int(
            max_tokens if max_tokens is not None
            else getenv("MXNET_GEN_MAX_TOKENS", 256))
        self._default_deadline_s = (
            float(default_deadline_ms) / 1e3 if default_deadline_ms
            is not None
            else float(getenv("MXNET_SERVING_DEADLINE_MS", 0)) / 1e3)
        # host mirrors of the per-slot step inputs: last token plus the
        # (seed, counter base, temperature, top_k, top_p, method)
        # sampling vectors — all traced operands of the ONE decode
        # executable.  The lanes change only at admission/retirement,
        # so their device mirrors (_samp_dev) are cached across
        # iterations; the per-token key counter is derived in-program
        # from the position operand
        self._last_tok = _np.zeros((self.max_slots,), _np.int32)
        self._samp = model.greedy_sampling(self.max_slots)
        self._samp_dev: Optional[Any] = None
        self._in_admission: List[GenRequest] = []
        self.iteration_log: Deque[Dict[str, Any]] = collections.deque(
            maxlen=self.LOG_KEEP)
        self._iter = 0
        self.warmed = 0
        self._tps_window: Deque[Tuple[float, int]] = collections.deque(
            maxlen=64)
        # worker-death/decode-fault recovery hook: when set (by
        # GenerationServer), sequences hit by a decode-step fault are
        # handed to it for resurrection instead of failed terminally;
        # signature sink(victims: List[GenRequest], exc, site: str)
        self.recovery_sink: Optional[Any] = None
        # speculative decoding: a DraftModel (or None when off).
        # Requests default to speculating whenever a draft exists;
        # per-request speculative=False opts out and mixed iterations
        # ride the verify program together (plain slots just keep only
        # the first verified token)
        self.spec_mode = str(
            spec_mode if spec_mode is not None
            else getenv("MXNET_GEN_SPEC_MODE", "off"))
        self.spec_k = int(
            spec_k if spec_k is not None
            else getenv("MXNET_GEN_SPEC_K", 4))
        spec_layers = int(
            spec_draft_layers if spec_draft_layers is not None
            else getenv("MXNET_GEN_SPEC_DRAFT_LAYERS", 0))
        from .speculation import make_draft
        self._draft = make_draft(
            self.spec_mode, model, self.spec_k, layers=spec_layers,
            draft_model=draft_model, max_slots=self.max_slots,
            buckets=self.grid)
        self._spec_proposed = 0
        self._spec_accepted = 0

    # -- lifecycle ----------------------------------------------------------
    def warmup(self) -> int:
        """Pre-compile the full program grid — prefill x prompt
        buckets, suffix prefill x (prefix, suffix) bucket pairs, the
        first-token selector, decode x KV buckets, admission
        row-writes x both, prefix-row shrinks — so steady-state
        traffic never compiles, including across per-request sampling
        parameter changes and shared-prefix admissions."""
        self.warmed = self.model.warmup(
            self.cache, self.prompt_buckets,
            suffix_pairs=self.cache.prefix.slots > 0)
        self.warmed += self.cache.warmup_writes(self.prompt_buckets)
        if self._draft is not None:
            self.warmed += self._draft.warmup(self.prompt_buckets)
            self.warmed += self._warmup_spec()
        return self.warmed

    def _warmup_spec(self) -> int:
        """Pre-compile the speculative pair — the draft-proposal chain
        and the (k+1)-token verify pass — for every KV bucket, so
        speculative steady-state traffic compiles nothing either."""
        S = self.cache.max_slots
        toks = _np.zeros((S,), _np.int32)
        pos = _np.zeros((S,), _np.int32)
        n = 0
        for b in self.cache.grid:
            self.cache.bucket = int(b)
            self.cache._alloc_buffers(self.cache.bucket)
            drafts = self._draft.propose(self.cache, toks, pos)
            cand = _np.concatenate(
                [toks[:, None], _np.asarray(drafts, _np.int32)],
                axis=1)
            self.model.verify(self.cache, cand, pos)
            n += 2
        self.cache.bucket = self.cache.grid[0]
        self.cache._alloc_buffers(self.cache.bucket)
        return n

    def close(self) -> None:
        """Fail everything in flight and stop admissions."""
        self.scheduler.close()
        for slot, req in self.scheduler.active().items():
            self.scheduler.release(slot)
            self.cache.free(slot)
            req.fail(MXNetError(
                "generation engine stopped with the sequence still "
                "decoding (shutdown)"))
            _metrics.GEN_RETIREMENTS_TOTAL.labels(reason="error").inc()
        _metrics.GEN_SLOTS_ACTIVE.set(0)

    def evacuate(self) -> Tuple[List[GenRequest], List[GenRequest]]:
        """Strip every request out of the engine WITHOUT failing its
        stream — the worker-death path: the supervisor resurrects them
        on a healthy replica.  Returns ``(queued, resident)``; resident
        entries still carry their emitted-token transcript on their
        streams.  The engine is left empty with fresh KV buffers (the
        death may have landed mid-step, after the old buffers were
        donated)."""
        queued = [r for r in self.scheduler.drain_queue()
                  if not r.is_cancelled()]
        resident: List[GenRequest] = []
        for slot, req in self.scheduler.active().items():
            self.scheduler.release(slot)
            self.cache.free(slot)
            if req.stream.finished or req.is_cancelled():
                continue
            resident.append(req)
        # a death mid-prefill strands its request in neither queue nor
        # slot table — it is recoverable all the same (a death between
        # activate and the bookkeeping line can leave it in both: dedup)
        for req in self._in_admission:
            if req not in resident and not req.stream.finished \
                    and not req.is_cancelled():
                resident.append(req)
        self._in_admission = []
        self.cache.reset_buffers()
        if self._draft is not None:
            self._draft.evacuate()
        # fresh lanes: stale sampling methods on freed slots would
        # keep steering the step into its sampler branch for nothing
        self._samp = self.model.greedy_sampling(self.max_slots)
        self._samp_dev = None
        _metrics.GEN_SLOTS_ACTIVE.set(0)
        return queued, resident

    # -- request API --------------------------------------------------------
    def _validate_sampling(self, method: str, temperature: float,
                           top_k: int, top_p: float, seed: int) -> int:
        """The zoo's validation rules (``model_zoo.generation``), so
        the HTTP layer's 400s match the in-process API: method must be
        known, temperature > 0, top_k >= 1 (clamped to the vocab),
        0 < top_p <= 1.  Returns the clamped top_k."""
        if method not in METHOD_CODES:
            raise MXNetError(
                f"unknown generation method {method!r} (expected "
                "greedy, sample, top_k, or top_p)")
        if not temperature > 0.0:
            raise MXNetError(
                f"temperature must be > 0, got {temperature}")
        if not 1 <= top_k:
            raise MXNetError(f"top_k must be >= 1, got {top_k}")
        if not 0.0 < top_p <= 1.0:
            raise MXNetError(f"top_p must be in (0, 1], got {top_p}")
        if not -2**31 <= int(seed) < 2**31:
            # the seed rides the compiled step as an int32 operand; an
            # out-of-range value must be the caller's 400, not a
            # mid-admission numpy OverflowError retiring the stream as
            # a server error
            raise MXNetError(
                f"seed must fit int32 (got {seed})")
        return min(int(top_k), int(self.model.vocab_size))

    def submit(self, tokens: Any, max_new_tokens: int = 64,
               eos_token: Optional[int] = None,
               deadline_ms: Optional[float] = None,
               method: Optional[str] = None,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               seed: Optional[int] = None,
               speculative: Optional[bool] = None) -> TokenStream:
        """Queue one prompt; returns its :class:`TokenStream`.  Sheds
        with :class:`OverloadError` when the admission queue is full;
        rejects (plain ``MXNetError``) prompts whose budget cannot fit
        the KV/position ceiling, or whose sampling parameters are out
        of range — those are the caller's bugs, not load.  Sampling
        (``method`` sample/top_k/top_p with ``temperature``/``top_k``/
        ``top_p``) runs on the device under per-slot counter-PRNG keys
        derived from ``seed``: same seed => same stream, across
        worker-death resurrection included.  ``speculative`` defaults
        to whether the engine has a draft (MXNET_GEN_SPEC_MODE);
        ``False`` opts this request out of drafting, ``True`` on an
        engine without a draft quietly decodes plain — either way the
        token stream is the same bytes."""
        toks = _np.asarray(tokens, _np.int32).reshape(-1)
        if toks.size < 1:
            raise MXNetError("empty prompt")
        method = str(method) if method is not None \
            else self.default_method
        temperature = float(temperature) if temperature is not None \
            else self.default_temperature
        top_k = int(top_k) if top_k is not None else self.default_top_k
        top_p = float(top_p) if top_p is not None else self.default_top_p
        seed = int(seed) if seed is not None else 0
        top_k = self._validate_sampling(method, temperature, top_k,
                                        top_p, seed)
        if self.max_tokens_cap > 0:
            max_new_tokens = min(int(max_new_tokens),
                                 self.max_tokens_cap)
        if max_new_tokens < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        need = int(toks.size) + int(max_new_tokens)
        if need > self.grid[-1]:
            raise MXNetError(
                f"prompt ({toks.size}) + max_new_tokens "
                f"({max_new_tokens}) needs {need} positions; the top "
                f"KV bucket / model ceiling is {self.grid[-1]} "
                "(raise MXNET_GEN_KV_BUCKETS or shorten the request)")
        if deadline_ms is None and self._default_deadline_s > 0:
            deadline_ms = self._default_deadline_s * 1e3
        deadline_t = (time.monotonic() + deadline_ms / 1e3
                      if deadline_ms else None)
        spec = bool(speculative) if speculative is not None \
            else self._draft is not None
        req = GenRequest(toks, max_new_tokens, eos_token, deadline_t,
                         method=method, temperature=temperature,
                         top_k=top_k, top_p=top_p, seed=seed,
                         speculative=spec)
        # consumer cancel while still queued -> evict NOW (queue budget
        # frees immediately; an abandoned-request flood cannot hold
        # queue_full sheds high until the next admission pass)
        req.stream._on_cancel = lambda: self.scheduler.discard(req)
        self.scheduler.submit(req)      # raises OverloadError on shed
        return req.stream

    def submit_request(self, req: GenRequest, front: bool = False) -> None:
        """Install an already-accepted request (the recovery path):
        bypasses the queue_full shed — the request was admitted once
        and must complete or fail structurally, never re-shed."""
        req.stream._on_cancel = lambda: self.scheduler.discard(req)
        self.scheduler.submit(req, front=front, force=True)

    # -- the scheduling quantum ---------------------------------------------
    def run_iteration(self) -> bool:
        """Retire -> admit -> decode, once.  Returns True when any work
        happened (False = idle: nothing active, nothing admissible)."""
        from .. import faults as _faults
        from .. import health as _health

        self._iter += 1
        log: Dict[str, Any] = {"iter": self._iter, "admitted": [],
                               "retired": [], "decoded": []}

        # 1. retire: EOS/max-tokens were marked at the previous decode;
        #    cancelled consumers release their slot here too.  The
        #    producer-side `finished` flag, NOT `done`: a finished
        #    sequence must free its slot even while its consumer is
        #    still draining buffered tokens
        for slot, req in self.scheduler.active().items():
            if req.stream.finished or req.is_cancelled():
                self._retire(slot, req,
                             req.stream.finish_reason or "cancelled")
                log["retired"].append(slot)

        # 2. admit into free slots (prefill, one compiled program per
        #    prompt bucket).  Always visit the queue — with zero free
        #    slots pop_admissions(0) admits nothing but STILL sheds
        #    queued requests whose deadline passed ("no slot freed
        #    within the deadline" is the generation overload signal).
        #    Mid-admission requests ride self._in_admission so a
        #    worker death during prefill still evacuates them (they
        #    are in neither the queue nor the slot table), and the
        #    scheduler's mid-admission count keeps drain polls honest.
        free = self.cache.free_slots()
        pending = self.scheduler.pop_admissions(len(free))
        self._in_admission = list(pending)
        for req in pending:
            try:
                # prefill lands in the REQUEST's trace (attach), not an
                # engine-iteration trace; a failed prefill marks the
                # span errored, which tail-upgrades the whole trace
                with _tracing.attach(req.trace), _tracing.child_span(
                        "engine.prefill", request_id=req.request_id,
                        prompt=int(req.tokens.size)):
                    slot = self._admit(req)
            except Exception as e:   # noqa: BLE001 - a poisoned
                # prompt (or an injected prefill fault) fails ONLY
                # its own request; the engine keeps serving
                req.fail(e)
                REQUESTS_TOTAL.labels(status="error").inc()
                _metrics.GEN_RETIREMENTS_TOTAL.labels(
                    reason="error").inc()
            else:
                log["admitted"].append(slot)
            # NOT in a finally: a BaseException mid-prefill must leave
            # the request visible to evacuate()
            self._in_admission.remove(req)
            self.scheduler.admission_done()

        active = self.scheduler.active()
        _metrics.GEN_SLOTS_ACTIVE.set(len(active))
        if not active:
            self.cache.reset_if_empty()
            if self._draft is not None:
                self._draft.reset_if_empty()
            self.iteration_log.append(log)
            return bool(log["admitted"] or log["retired"])

        # 3. one resident decode step over EVERY active slot.  The
        #    iteration span is its own (head-sampled) trace — one step
        #    serves MANY requests, so it cannot be a child of any one
        #    of them; instead it LINKS every resident request's trace
        #    id, and a request's trace finds "its" decode steps by
        #    searching iteration spans that link it.
        #    When any resident request speculates, the WHOLE iteration
        #    rides the draft+verify pair (one program each): the draft
        #    proposes k tokens per slot, verify scores all k+1
        #    positions in one target pass, and plain slots simply keep
        #    only the first verified token — which is bit-identical to
        #    what the plain step would have produced.
        spec_k = self._draft.k if self._draft is not None else 0
        spec_slots = frozenset(
            s for s, r in active.items()
            if spec_k and getattr(r, "speculative", False))
        use_spec = bool(spec_slots)
        iter_tid = None
        try:
            with _tracing.span("engine.iteration", iter=self._iter,
                               slots=len(active)) as isp:
                iter_tid = _tracing.current_trace_id()
                for _r in active.values():
                    _tr = getattr(_r, "trace", None)
                    if _tr is not None:
                        isp.add_link(_tr.trace_id)
                _faults.maybe_fault("serving.execute", phase="decode",
                                    slots=len(active))
                if use_spec:
                    # verify scatters k rows past every slot's
                    # position: grow for the worst case up front,
                    # capped at the grid top (rows past it belong to
                    # tokens the submit-time budget check proves are
                    # never emitted)
                    self.cache.ensure_capacity(
                        min(self.cache.needed_capacity() + spec_k,
                            self.grid[-1]))
                else:
                    self.cache.ensure_capacity(
                        self.cache.needed_capacity())
                pos = _np.maximum(self.cache.positions,
                                  0).astype(_np.int32)
                if self._samp_dev is None:
                    self._samp_dev = self.model.device_sampling(
                        self._samp)
                if use_spec:
                    with _tracing.child_span(
                            "engine.draft",
                            slots=len(spec_slots), k=spec_k):
                        drafts = self._draft.propose(
                            self.cache, self._last_tok, pos,
                            self._samp_dev)
                    cand = _np.concatenate(
                        [self._last_tok[:, None],
                         _np.asarray(drafts, _np.int32)], axis=1)
                    with _health.watch_section("generation.step",
                                               slots=len(active)):
                        with _tracing.child_span(
                                "engine.verify",
                                slots=len(active), k=spec_k):
                            ver = self.model.verify(
                                self.cache, cand, pos,
                                self._samp_dev)
                else:
                    with _health.watch_section("generation.step",
                                               slots=len(active)):
                        next_tok = self.model.step(self.cache,
                                                   self._last_tok,
                                                   pos, self._samp_dev)
        except Exception as e:   # noqa: BLE001 - an iteration fault
            # hits exactly the sequences IN FLIGHT at this iteration
            # (their kv rows are suspect); queued requests and the
            # engine itself are unaffected.  The step consumed the KV
            # buffers by donation, so a raise AFTER dispatch leaves the
            # cache holding deleted arrays — reallocate before the next
            # admission touches them
            self.cache.reset_buffers()
            if self._draft is not None:
                # the draft's own buffers may have been donated to a
                # dispatch this fault interrupted
                self._draft.reset()
            victims: List[GenRequest] = []
            for slot, req in active.items():
                if self.recovery_sink is not None \
                        and not req.stream.finished \
                        and not req.is_cancelled():
                    # managed engine: the sequence is resurrected from
                    # its stream transcript (exactly-once recovery) —
                    # release the slot WITHOUT closing the stream
                    self.scheduler.release(slot)
                    self.cache.free(slot)
                    if self._draft is not None:
                        self._draft.release(slot)
                    if self._samp[5][slot]:
                        self._samp[5][slot] = 0
                        self._samp_dev = None
                    _metrics.GEN_RETIREMENTS_TOTAL.labels(
                        reason="recovered").inc()
                    victims.append(req)
                else:
                    req.fail(e)          # before close(): the consumer
                    #                      must observe the fault, not
                    #                      a clean end-of-stream
                    self._retire(slot, req, "error")
                    REQUESTS_TOTAL.labels(status="error").inc()
                log["retired"].append(slot)
            self.iteration_log.append(log)
            if victims:
                self.recovery_sink(victims, e, "decode")
            return True

        now = time.monotonic()
        n_streamed = 0
        it_proposed = it_accepted = 0
        for slot, req in active.items():
            if use_spec:
                p = int(self.cache.positions[slot])
                row = ver[slot]
                if slot in spec_slots:
                    # accept rule: keep the longest prefix of draft
                    # proposals that MATCH the target's own tokens —
                    # every emitted token is the target's, so the
                    # stream is byte-identical to non-speculative
                    a = 0
                    while a < spec_k \
                            and int(cand[slot, a + 1]) == int(row[a]):
                        a += 1
                    it_proposed += spec_k
                    it_accepted += a
                    _metrics.GEN_SPEC_PROPOSED_TOKENS_TOTAL.inc(spec_k)
                    if a:
                        _metrics.GEN_SPEC_ACCEPTED_TOKENS_TOTAL.inc(a)
                    if spec_k - a:
                        _metrics.GEN_SPEC_REJECTED_TOKENS_TOTAL.inc(
                            spec_k - a)
                    emit_n = a + 1
                else:
                    # plain slot riding a speculative iteration: its
                    # verify column 0 IS the plain step's token
                    emit_n = 1
                emit_n = min(emit_n,
                             req.max_new_tokens - req.emitted)
                emit = [int(row[j]) for j in range(emit_n)]
                if req.eos_token is not None:
                    eos = int(req.eos_token)
                    for j, t in enumerate(emit):
                        if t == eos:
                            del emit[j + 1:]
                            break
                m = len(emit)
                # verify advanced every slot's device rows to p+k+1;
                # adopt them, then roll the rejected/unemitted tail
                # back.  Plain slots just take their one real row —
                # the extra rows were never theirs (bookkeeping, not a
                # rollback)
                if slot in spec_slots and m < spec_k + 1:
                    self.cache.positions[slot] = p + spec_k + 1
                    self.cache.truncate(slot, p + m)
                else:
                    self.cache.positions[slot] = p + m
                if self._draft is not None:
                    self._draft.commit(slot, p + m)
                self._last_tok[slot] = emit[-1]
                _metrics.GEN_SAMPLED_TOKENS_TOTAL.labels(
                    method=req.method).inc(m)
                # ONE lock pass / consumer wakeup for the whole run;
                # absolute indexes ride along as with put
                req.stream.put_many(
                    emit, start_index=req.offset + req.emitted)
                req.emitted += m
                n_streamed += m
                tok = emit[-1]
                if slot in spec_slots:
                    # min-exemplar retention: the histogram keeps the
                    # trace id of the WORST-accepting recent step
                    _metrics.GEN_SPEC_ACCEPTED_PER_STEP.observe(
                        float(m), exemplar=iter_tid)
            else:
                tok = int(next_tok[slot])
                self.cache.positions[slot] += 1
                self._last_tok[slot] = tok
                _metrics.GEN_SAMPLED_TOKENS_TOTAL.labels(
                    method=req.method).inc()
                # absolute index rides along: the stream dedupes
                # replays from recovered producers at this boundary
                req.stream.put(tok, index=req.offset + req.emitted)
                req.emitted += 1
                n_streamed += 1
            log["decoded"].append(slot)
            finished = None
            if req.eos_token is not None and tok == int(req.eos_token):
                finished = "eos"
            elif req.emitted >= req.max_new_tokens:
                finished = "length"
            elif int(self.cache.positions[slot]) >= self.grid[-1]:
                finished = "length"
            if finished:
                # mark done now; the slot frees at the next iteration's
                # retire phase (keeps this loop allocation-free)
                req.stream.close(finished)
        if it_proposed:
            self._spec_proposed += it_proposed
            self._spec_accepted += it_accepted
            _metrics.GEN_SPEC_ACCEPT_RATE.set(
                self._spec_accepted / self._spec_proposed)
        _metrics.GEN_TOKENS_TOTAL.labels(phase="decode").inc(n_streamed)
        _metrics.GEN_ITERATIONS_TOTAL.inc()
        self._tps_window.append((now, n_streamed))
        if len(self._tps_window) >= 2:
            t0, _ = self._tps_window[0]
            span = now - t0
            if span > 0:
                total = sum(n for _, n in self._tps_window) \
                    - self._tps_window[0][1]
                _metrics.GEN_TOKENS_PER_SECOND.set(total / span)
        self.iteration_log.append(log)
        return True

    def _lookup_prefix(self, req: GenRequest) -> Optional[Any]:
        """The longest resident prefix of ``req``'s prompt (pinned —
        the caller unpins), or None.  Candidates are the bucket-aligned
        prefix lengths: the prompt-bucket grid values <= the prompt
        length, longest first.  A whole-prompt entry only counts when
        it carries its prefill logits (nothing left to prefill), and a
        partial prefix only when the padded layout it forces
        (``q + round_up(suffix)`` rows) needs no more capacity than a
        cold prefill's own padded prompt — a SHORT resident prefix
        under a LONG prompt would otherwise pad past the cold layout
        (ballooning the whole cache's bucket, or, past the top bucket,
        hard-failing a request a cold prefill serves fine)."""
        t0 = int(req.tokens.size)
        for q in reversed(self.prompt_buckets):
            if q > t0:
                continue
            key = prefix_key(req.tokens, q)
            e = self.cache.prefix.lookup(key, pin=True)
            if e is None:
                continue
            if e.q == t0:
                if e.logits is None:
                    self.cache.prefix.unpin(key)
                    continue
                return e
            sb = round_up_bucket(t0 - q, self.prompt_buckets)
            if q + sb > round_up_bucket(t0, self.prompt_buckets):
                self.cache.prefix.unpin(key)
                continue        # reuse must never cost more than cold
            return e
        return None

    def _insert_prefix(self, req: GenRequest, ks: Sequence[Any],
                       vs: Sequence[Any], logits: _np.ndarray) -> None:
        """After a cold prefill, park the longest bucket-aligned prefix
        of the prompt in the pinned region (rows sliced off the
        prefill output — a warmable shape-pair program).  When the
        prefix IS the whole prompt, the prefill logits ride along so
        an identical prompt admits with no model call at all."""
        t0 = int(req.tokens.size)
        q = max((b for b in self.prompt_buckets if b <= t0),
                default=None)
        if q is None:
            return
        key = prefix_key(req.tokens, q)
        if self.cache.prefix.lookup(key) is not None:
            if q == t0:
                # the resident entry was cut from a longer prompt and
                # carries no logits; this cold prefill just computed
                # them for exactly this prefix — attach, so identical
                # prompts now admit with no model call
                self.cache.prefix.attach_logits(key, logits)
            return
        if q < int(ks[0].shape[0]):
            cut = _shrink_rows(list(ks) + list(vs), q)
            pks, pvs = cut[:len(ks)], cut[len(ks):]
        else:
            pks, pvs = list(ks), list(vs)
        self.cache.prefix.insert(
            key, pks, pvs, q, logits=(logits if q == t0 else None))

    def _admit(self, req: GenRequest) -> int:
        """Install one request in a slot.  A cold prompt runs prefill
        (and parks its bucket-aligned prefix for the next request); a
        prompt whose prefix is resident COPIES the shared rows into
        the slot (one fused row-write over every layer) and prefills only the
        suffix — or nothing at all for an identical prompt.  Either
        way the pass emits the FIRST generated token (TTFT ends
        here)."""
        from .. import faults as _faults
        _faults.maybe_fault("serving.execute", phase="prefill",
                            prompt=int(req.tokens.size))
        slot = self.cache.alloc()
        if slot is None:                     # caller checked free_slots
            raise MXNetError("no free decode slot (admission race)")
        entry = None
        try:
            t0 = int(req.tokens.size)
            cacheable = (self.cache.prefix.slots > 0
                         and t0 >= self.prompt_buckets[0])
            if cacheable:
                entry = self._lookup_prefix(req)
            if entry is not None and entry.q == t0:
                # identical prompt: pure row copy + cached logits —
                # no model invocation on the admission path
                self.cache.write_prompt(slot, entry.ks, entry.vs, t0)
                logits = entry.logits
                _metrics.GEN_PREFIX_HITS_TOTAL.inc()
            elif entry is not None:
                # shared prefix: copy the resident rows, prefill only
                # the suffix against them
                q = entry.q
                sb = round_up_bucket(t0 - q, self.prompt_buckets)
                logits, sks, svs = self.model.prefill_suffix(
                    req.tokens[q:], entry.ks, entry.vs, q, sb)
                self.cache.write_prompt(slot, entry.ks, entry.vs, q)
                self.cache.write_prompt(slot, sks, svs, t0, start=q)
                _metrics.GEN_PREFIX_HITS_TOTAL.inc()
            else:
                pb = round_up_bucket(t0, self.prompt_buckets)
                logits, ks, vs = self.model.prefill(req.tokens, pb)
                self.cache.write_prompt(slot, ks, vs, t0)
                if cacheable:
                    _metrics.GEN_PREFIX_MISSES_TOTAL.inc()
                    self._insert_prefix(req, ks, vs, logits)
            # first token through the same fused sampler as the step
            # (key = fold_in(PRNGKey(seed), offset)): one key stream
            # per request no matter which program emits which token
            first = self.model.select(
                logits, req.seed, req.offset, req.temperature,
                req.top_k, req.top_p, METHOD_CODES[req.method])
            if req.speculative and self._draft is not None:
                # the draft follows the same prompt: its cache rows
                # mirror this slot from the first iteration on
                self._draft.admit(slot, req.tokens,
                                  self.prompt_buckets)
        except Exception:
            self.cache.free(slot)
            if self._draft is not None:
                self._draft.release(slot)
            raise
        finally:
            if entry is not None:
                self.cache.prefix.unpin(entry.key)
        self.scheduler.activate(slot, req)
        req.slot = slot
        self._last_tok[slot] = first
        # arm the slot's sampling lane.  The counter base makes the
        # in-program key counter (pos - base) equal the token's
        # absolute stream index: at the request's decode step number e
        # (tokens emitted so far, prefill's included), pos is
        # t0 + e - 1, and the token being drawn is index offset + e —
        # so base = t0 - offset - 1, a per-request constant (for a
        # resurrection, exactly the original prompt length minus one)
        self._samp[0][slot] = req.seed
        self._samp[1][slot] = t0 - req.offset - 1
        self._samp[2][slot] = req.temperature
        self._samp[3][slot] = req.top_k
        self._samp[4][slot] = req.top_p
        self._samp[5][slot] = METHOD_CODES[req.method]
        self._samp_dev = None        # lanes changed: remirror once
        req.t_first = time.monotonic()
        req.stream.put(first, index=req.offset)
        req.emitted = 1
        _metrics.GEN_SAMPLED_TOKENS_TOTAL.labels(
            method=req.method).inc()
        _metrics.GEN_TTFT_SECONDS.observe(
            req.t_first - req.enqueue_t,
            exemplar=req.trace.trace_id if req.trace is not None
            else None)
        _metrics.GEN_TOKENS_TOTAL.labels(phase="prefill").inc()
        _metrics.GEN_ADMISSIONS_TOTAL.inc()
        if req.recover_t0 is not None:
            # recovery ends when the resurrected sequence streams again
            _metrics.SERVING_RECOVERY_SECONDS.observe(
                req.t_first - req.recover_t0)
            req.recover_t0 = None
        if req.eos_token is not None and first == int(req.eos_token):
            req.stream.close("eos")
        elif req.emitted >= req.max_new_tokens:
            req.stream.close("length")
        return slot

    def _retire(self, slot: int, req: GenRequest, reason: str) -> None:
        self.scheduler.release(slot)
        self.cache.free(slot)
        if self._draft is not None:
            self._draft.release(slot)
        if self._samp[5][slot]:
            self._samp[5][slot] = 0      # freed lanes ride greedy
            self._samp_dev = None
        req.stream.close(reason)         # no-op if already closed
        if reason in ("eos", "length"):
            REQUESTS_TOTAL.labels(status="ok").inc()
        _metrics.GEN_RETIREMENTS_TOTAL.labels(reason=reason).inc()

    # -- introspection ------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        return {
            "model": self.model.describe(),
            "cache": self.cache.describe(),
            "slots": {"max": self.max_slots,
                      "active": self.scheduler.n_active(),
                      "free": len(self.cache.free_slots())},
            "queue": {"depth": len(self.scheduler),
                      "limit": self.scheduler.queue_limit},
            "prompt_buckets": list(self.prompt_buckets),
            "kv_buckets": list(self.grid),
            "max_tokens_cap": self.max_tokens_cap,
            "warmed_programs": self.warmed,
            "iterations": self._iter,
            "sampling_defaults": {
                "method": self.default_method,
                "temperature": self.default_temperature,
                "top_k": self.default_top_k,
                "top_p": self.default_top_p,
            },
            "prefix_cache": self.cache.prefix.describe(),
            "speculation": (self._draft.describe()
                            if self._draft is not None
                            else {"mode": "off"}),
        }
