"""KVStore — key-value gradient aggregation / parameter sync.

Reference parity (leezu/mxnet): ``python/mxnet/kvstore.py`` +
``src/kvstore/`` (KVStoreLocal 'local'/'device', KVStoreNCCL 'nccl',
KVStoreDist 'dist_sync'/'dist_async' over ps-lite) — SURVEY.md sections
2.3 / 3.5.

Design (tpu-first, the SURVEY "north star"): the entire server/ZMQ stack
collapses into SPMD collectives:

* ``'local'`` / ``'device'`` — single-process store. With one chip it's a
  dict; with a mesh-sharded batch the reduction already happened inside the
  compiled step (XLA inserted the psum), so push/pull are identity+store.
* ``'nccl'`` → alias of 'device' (collectives are XLA's job on TPU).
* ``'ici'`` (new canonical name; 'dist_sync'/'dist_device_sync' alias it) —
  multi-host SPMD over a ``jax.distributed``-initialized pod: push performs
  ``jax.lax.psum`` of gradients over the global mesh's data axis via a tiny
  jitted allreduce program; rank/num_workers map to process index/count.
* ``'dist_async'`` — the host-driven parameter service (SURVEY.md 5.8):
  TCP servers started by ``tools/launch.py -s S`` apply the optimizer
  immediately per worker push (Hogwild). See ``kvstore_async.py``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import time

import jax
import jax.numpy as jnp

from . import metrics as _metrics
from .base import MXNetError, getenv, register_env
from .ndarray.ndarray import NDArray

__all__ = ["KVStore", "create"]

register_env("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000,
             "Element count that closes a gradient-reduction bucket: "
             "smaller arrays pushed together flatten/concat into one "
             "fused cross-process collective per bucket (the reference "
             "sliced big arrays across servers at this bound; here it "
             "bounds the fusion buffer), larger arrays reduce alone.")

KV_RAW_BYTES = _metrics.counter(
    "mxnet_kv_raw_bytes_total",
    "Raw (uncompressed f32/bf16) gradient bytes offered to the "
    "cross-process wire, by configured compression codec — the "
    "denominator of the EQuARX compression win.  Fed by the ICI "
    "packed collectives and the dist_async push encoders.",
    labels=("ctype",))
KV_COMPRESSED_BYTES = _metrics.counter(
    "mxnet_kv_compressed_bytes_total",
    "Post-codec payload bytes that actually crossed the wire, by "
    "compression codec (equals mxnet_kv_raw_bytes_total for "
    "ctype='none').  compressed/raw is the effective wire compression "
    "ratio; tools/bandwidth.py --compression reports it per ctype "
    "offline.", labels=("ctype",))

register_env("MXNET_PS_CONNECT_TIMEOUT", 120,
             "Seconds a dist_async worker retries connecting to its "
             "parameter server before failing (server cold start).")

register_env("MXNET_PS_BARRIER_TIMEOUT", 600,
             "Seconds a parameter-server barrier waits for all workers "
             "before raising (kvstore='dist_async').")


# ---------------------------------------------------------------------------
# Lossy gradient codecs (reference: src/kvstore/gradient_compression.cc;
# the int8 blockwise scheme is the EQuARX-style quantized-collective
# mapping SURVEY.md 5.8 prescribes for TPU). Module-level and pure so the
# same functions serve the local lossy-channel path, the ICI packed
# collectives, and the unit tests.
# ---------------------------------------------------------------------------

def _quantize_2bit(acc, threshold):
    """f32-ish vector -> (packed uint8 codes [4 codes/byte], dequantized
    values). Codes: 0 -> -t, 1 -> 0, 2 -> +t."""
    t = jnp.asarray(threshold, jnp.float32)
    accf = acc.astype(jnp.float32)
    codes = jnp.where(accf >= t, jnp.uint8(2),
                      jnp.where(accf <= -t, jnp.uint8(0), jnp.uint8(1)))
    n = codes.shape[0]
    pad = (-n) % 4
    c4 = jnp.pad(codes, (0, pad), constant_values=1).reshape(-1, 4)
    packed = (c4[:, 0] | (c4[:, 1] << 2) | (c4[:, 2] << 4)
              | (c4[:, 3] << 6))
    deq = (codes.astype(jnp.float32) - 1.0) * t
    return packed, deq.astype(acc.dtype)


def _dequantize_2bit(packed, n, threshold, dtype=jnp.float32):
    """Packed uint8 codes -> value vector of length n."""
    t = jnp.asarray(threshold, jnp.float32)
    parts = [(packed >> s) & 3 for s in (0, 2, 4, 6)]
    codes = jnp.stack(parts, axis=1).reshape(-1)[:n]
    return ((codes.astype(jnp.float32) - 1.0) * t).astype(dtype)


_INT8_BLOCK = 256


def _quantize_int8(flat):
    """Blockwise max-abs int8: returns (codes int8 [padded to block
    multiple], scales f32 [one per block], n)."""
    f = flat.astype(jnp.float32)
    n = f.shape[0]
    pad = (-n) % _INT8_BLOCK
    blocks = jnp.pad(f, (0, pad)).reshape(-1, _INT8_BLOCK)
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    codes = jnp.clip(jnp.round(blocks / scale), -127, 127).astype(jnp.int8)
    return codes.reshape(-1), scale[:, 0], n


def _dequantize_int8(codes, scales, n, dtype=jnp.float32):
    vals = (codes.reshape(-1, _INT8_BLOCK).astype(jnp.float32)
            * scales[:, None]).reshape(-1)[:n]
    return vals.astype(dtype)


class KVStore:
    """Single-process store ('local'/'device'/'nccl')."""

    def __init__(self, kv_type: str = "local") -> None:
        self._wire_compressed = False  # True on stores whose reduce
        #                                path applies the codec itself
        self.type = kv_type
        self._store: Dict[Any, NDArray] = {}
        self._updater: Optional[Callable] = None
        self._compression: Dict[str, Any] = {}

    # -- core API ----------------------------------------------------------
    @staticmethod
    def _pair(key: Any, value: Any):
        """Normalize (key, value) to parallel lists. A list value under a
        single key is that key's per-device value list (CommDevice input),
        not a multi-key batch."""
        if isinstance(key, (list, tuple)):
            vals = [None] * len(key) if value is None else list(value)
            return list(key), vals
        return [key], [value]

    def init(self, key: Any, value: Union[NDArray, Sequence[NDArray]]) -> None:
        keys, vals = self._pair(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            self._store[k] = v.copy()

    def push(self, key: Any, value: Union[NDArray, Sequence[NDArray]],
             priority: int = 0) -> None:
        """Push value(s) into the store (gradient reduction entry).

        ``priority`` orders reduction DISPATCH, reference-style: higher
        values cross the wire first (the gluon Trainer passes
        ``-param_index`` so the parameters the next forward needs first
        arrive first).  It may be an int applied to every key of a
        batched push, or a per-key list.  Bucket *composition* never
        depends on it — membership is fixed by key order and the byte
        budget, which keeps the 2-bit error-feedback residuals
        deterministic — only the order buckets execute in does."""
        _metrics.KVSTORE_PUSHES.inc()
        t0 = time.perf_counter()
        try:
            self._push(key, value, priority)
        finally:
            _metrics.COLLECTIVE_SECONDS.labels(collective="push") \
                .observe(time.perf_counter() - t0)
            self._synth_wire_sleep(key, value)

    @staticmethod
    def _synth_wire_sleep(key: Any, value: Any) -> None:
        """The calibrated synthetic-slow-wire knob
        (``MXNET_KV_SYNTH_WIRE_GBPS``): model a wire of that many
        gigabytes/sec by sleeping raw_bytes / rate after the push.
        Charged identically on the serialized and the overlapped
        (comm-thread) paths, so the dist-comm-smoke ratio measures the
        schedule, not a bookkeeping asymmetry."""
        gbps = float(getenv("MXNET_KV_SYNTH_WIRE_GBPS", 0.0))
        if gbps <= 0:
            return
        vals = value if isinstance(key, (list, tuple)) else [value]
        nbytes = 0
        for v in vals:
            v0 = v[0] if isinstance(v, (list, tuple)) else v
            try:
                # a real wire cannot transmit an unmaterialized
                # gradient: block until THIS call's payload exists on
                # the host side (exactly what the dist_async client's
                # asnumpy does), then charge the transmission time.
                # Serialized pushes therefore block on the whole
                # backward; scheduled per-bucket pushes block only on
                # their bucket's segments — the overlap being measured.
                import jax as _jax
                _jax.block_until_ready(v0._data)
                nbytes += int(v0.size) * int(
                    getattr(v0.dtype, "itemsize", 4))
            except Exception:   # noqa: BLE001 - sizeless value
                pass
        if nbytes:
            time.sleep(nbytes / (gbps * 1e9))

    def _push(self, key: Any, value: Union[NDArray, Sequence[NDArray]],
              priority: int = 0) -> None:
        keys, vals = self._pair(key, value)
        # on the multi-host store the codec is applied at the wire (the
        # packed collective in _reduce_flat_compressed) — compressing
        # again here would quantize twice and clip summed code points
        local_lossy = bool(self._compression) and not self._wire_compressed
        merged = []
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                if local_lossy:
                    # compress each device's contribution before the
                    # reduce — that's the traffic the reference's scheme
                    # targets (gradient_compression.cc)
                    v = [self._compress(k, i, x)
                         for i, x in enumerate(v)]
                # multi-device gradient lists reduce locally (CommDevice)
                from .ndarray import ops
                v = ops.add_n(*v)
            elif local_lossy:
                v = self._compress(k, 0, v)
            merged.append(v)
        # a multi-key push crosses the process boundary as a handful of
        # fused bucket collectives, not one collective per key
        prios = self._norm_priorities(keys, priority)
        for k, reduced in zip(keys,
                              self._allreduce_many(keys, merged, prios)):
            if self._updater is not None and k in self._store:
                self._updater(k, reduced, self._store[k])
            else:
                self._store[k] = reduced

    @staticmethod
    def _norm_priorities(keys: Sequence[Any], priority: Any) -> List[int]:
        """Normalize the push/pull ``priority`` argument (int, or a
        per-key list for batched calls) to one int per key."""
        if isinstance(priority, (list, tuple)):
            if len(priority) != len(keys):
                raise MXNetError(
                    f"priority list length {len(priority)} does not "
                    f"match {len(keys)} keys")
            return [int(p) for p in priority]
        return [int(priority)] * len(keys)

    def pull(self, key: Any, out: Union[NDArray, Sequence[NDArray], None] = None,
             priority: int = 0, ignore_sparse: bool = True) -> Optional[NDArray]:
        """Pull value(s) out of the store.  ``priority`` is accepted for
        API parity with the reference (and the scheduler's push
        ordering); pulls here are synchronous local reads, so it has
        no effect."""
        keys, outs = self._pair(key, out)
        results = []
        for k, o in zip(keys, outs):
            v = self._store.get(k)
            if v is None:
                raise MXNetError(f"key {k!r} was never init/pushed")
            if o is not None:
                targets = o if isinstance(o, (list, tuple)) else [o]
                for t in targets:
                    t._data = v._data
            results.append(v)
        return results[0] if not isinstance(key, (list, tuple)) else results

    def pushpull(self, key: Any, value: Any, out: Any = None,
                 priority: int = 0) -> None:
        self.push(key, value, priority)
        self.pull(key, out if out is not None else value, priority)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the listed rows as a RowSparseNDArray (reference:
        ``KVStore.row_sparse_pull`` — the sparse-embedding pull path)."""
        if row_ids is None:
            return self.pull(key, out, priority)
        from .ndarray.sparse import RowSparseNDArray
        import numpy as _onp
        v = self._store.get(key)
        if v is None:
            raise MXNetError(f"key {key!r} was never init/pushed")
        ids = row_ids.asnumpy() if isinstance(row_ids, NDArray) \
            else _onp.asarray(row_ids)
        ids = _onp.unique(ids.astype(_onp.int64))
        rows = v._data[ids]
        rsp = RowSparseNDArray(rows, ids.astype(_onp.int32),
                               tuple(v.shape), ctx=v.context)
        if out is not None:
            if not isinstance(out, RowSparseNDArray):
                # the reference errors on a dense out here
                # (kvstore_local.h PullRowSparseImpl CHECKs the stype)
                raise MXNetError(
                    "row_sparse_pull requires a row_sparse `out`, got "
                    f"stype {getattr(out, 'stype', 'default')!r}")
            out._sp_values = rsp._sp_values
            out._sp_indices = rsp._sp_indices
            out._sp_shape = rsp._sp_shape
            out._sp_dtype = rsp._sp_values.dtype
            out._dense_cache = None
            return out
        return rsp

    def _allreduce(self, v: NDArray) -> NDArray:
        return v  # single process: reduction already local

    def _allreduce_many(self, keys: Sequence[Any],
                        vals: Sequence[NDArray],
                        priorities: Optional[Sequence[int]] = None
                        ) -> List[NDArray]:
        return [self._allreduce(v) for v in vals]

    # -- config ------------------------------------------------------------
    def set_optimizer(self, optimizer: Any) -> None:
        """Run the optimizer inside the store (reference:
        update_on_kvstore; no server processes to pickle it to here)."""
        from .optimizer import get_updater
        self._updater = get_updater(optimizer)

    def set_gradient_compression(self, compression_params: Dict[str, Any]) -> None:
        """Gradient compression (reference:
        src/kvstore/gradient_compression.cc).

        type='2bit': per-push values quantize to {-threshold, 0,
        +threshold} with an error-feedback residual carried to the next
        push (the reference's scheme). type='fp16'/'bf16': dtype-compress
        the payload (the TPU-native cheap option). type='int8': blockwise
        max-abs-scaled int8 (beyond-reference: the EQuARX-style quantized
        collective, SURVEY.md 5.8) — ~4x less wire traffic at ~1/127
        blockwise relative error, no residual needed."""
        ctype = compression_params.get("type", "2bit")
        if ctype not in ("2bit", "fp16", "bf16", "int8", "none"):
            raise MXNetError(f"unknown compression type {ctype!r}")
        if ctype == "2bit" and float(
                compression_params.get("threshold", 0.5)) <= 0:
            raise MXNetError("2bit compression threshold must be > 0")
        self._compression = {} if ctype == "none" \
            else dict(compression_params, type=ctype)
        self._residuals: Dict[Any, NDArray] = {}
        self._ici_residuals: Dict[Any, Any] = {}   # per-key wire residuals

    def _compress(self, key: Any, slot: int, v: NDArray) -> NDArray:
        ctype = self._compression["type"]
        if ctype in ("fp16", "bf16"):
            dt = "float16" if ctype == "fp16" else "bfloat16"
            return v.astype(dt).astype(v.dtype)
        if ctype == "int8":
            flat = v._data.ravel()
            codes, scales, n = _quantize_int8(flat)
            deq = _dequantize_int8(codes, scales, n, flat.dtype)
            return NDArray(deq.reshape(v._data.shape), _wrap=True)
        thr = float(self._compression.get("threshold", 0.5))
        rkey = (key, slot)
        res = self._residuals.get(rkey)
        acc = v if res is None else v + res
        data = acc._data
        q = jnp.where(data >= thr, jnp.float32(thr),
                      jnp.where(data <= -thr, jnp.float32(-thr), 0.0)) \
            .astype(data.dtype)
        out = NDArray(q, _wrap=True)
        self._residuals[rkey] = NDArray(data - q, _wrap=True)
        return out

    def _set_updater(self, updater: Callable) -> None:
        self._updater = updater

    # -- topology ----------------------------------------------------------
    @property
    def rank(self) -> int:
        return 0

    @property
    def num_workers(self) -> int:
        return 1

    def barrier(self) -> None:
        from . import engine
        engine.waitall()

    def save_optimizer_states(self, fname: str, dump_weight: bool = False) -> None:
        import pickle
        with open(fname, "wb") as f:
            pickle.dump(getattr(self._updater, "states", {}), f)

    def load_optimizer_states(self, fname: str) -> None:
        import pickle
        with open(fname, "rb") as f:
            states = pickle.load(f)
        if self._updater is not None:
            self._updater.states = states

    def __repr__(self) -> str:
        return f"KVStore(type={self.type}, keys={len(self._store)})"


def _maybe_init_distributed() -> None:
    """Join the launcher-described multi-process job (idempotent; see
    base.join_distributed_job — mxnet_tpu/__init__ already does this at
    import when the env is present)."""
    from .base import join_distributed_job
    join_distributed_job()


class KVStoreICI(KVStore):
    """Multi-host synchronous data parallelism over ICI/DCN.

    Push of a per-process gradient sums it across all processes (the
    reference dist_sync invariant: pulled == sum over workers of pushed,
    ``tests/nightly/dist_sync_kvstore.py``). Mesh-sharded global arrays
    pass through unchanged — their reduction already happened inside the
    compiled SPMD step (XLA inserted the psum; SURVEY.md 3.5 TPU MAPPING).
    The reference's scheduler/server roles and key slicing disappear.
    """

    def __init__(self, kv_type: str = "ici") -> None:
        super().__init__(kv_type)
        _maybe_init_distributed()
        # one entry per executed bucket collective (introspection: the
        # bandwidth bench and the dist tests assert fusion happened)
        self._wire_compressed = True   # codec applied at the wire
        self.reduce_collectives = 0
        # bytes this process contributed to the wire across all reduces
        # (payload size after compression/packing) — introspection for
        # the bandwidth bench and the compression tests
        self.reduce_wire_bytes = 0
        self._reduce_progs: Dict[Any, Any] = {}
        self._reduce_mesh = None
        self._use_mesh_reduce: Optional[bool] = None

    @staticmethod
    def _needs_reduction(data) -> bool:
        try:
            # only a NON-fully-addressable array is a true global SPMD
            # array whose reduction already happened inside the compiled
            # step (summing again would multiply by N). A multi-device
            # but fully-addressable array is just this process's local
            # mesh replica (e.g. params mesh-placed by SPMDTrainer, then
            # trained through plain gluon.Trainer) — its gradient still
            # needs the cross-process sum.
            if len(data.devices()) > 1 and not data.is_fully_addressable:
                return False
        except Exception:
            pass
        return jax.process_count() > 1

    def _allreduce(self, v: NDArray) -> NDArray:
        return self._allreduce_many([0], [v])[0]

    def _allreduce_many(self, keys: Sequence[Any],
                        vals: Sequence[NDArray],
                        priorities: Optional[Sequence[int]] = None
                        ) -> List[NDArray]:
        """Cross-process sum of each value, bucketed: values needing
        reduction flatten/concat (per dtype) into fusion buffers of up to
        ``MXNET_KVSTORE_BIGARRAY_BOUND`` elements and each bucket crosses
        the wire as ONE collective (the reference's PSKV key slicing /
        BIGARRAY_BOUND aggregation, ``src/kvstore/kvstore_dist.h``);
        larger arrays reduce alone. All workers compute a bit-identical
        result — the reduction is one SPMD program over the global device
        mesh (or an ordered allgather+sum fallback), the dist_sync
        server-aggregation analog with no server processes.

        ``priorities`` (per key, higher first) order bucket DISPATCH
        only: composition stays a pure function of key order + sizes
        (the 2-bit residual determinism contract), and the order is the
        same deterministic function of (keys, priorities) on every
        rank, so SPMD collective sequences still match."""
        out: List[Optional[NDArray]] = [None] * len(vals)
        todo: List[int] = []
        for i, v in enumerate(vals):
            if self._needs_reduction(v._data):
                todo.append(i)
            else:
                out[i] = v
        bound = int(getenv("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000))
        # per-dtype buckets of index lists
        buckets: List[List[int]] = []
        cur: Dict[str, List[int]] = {}
        fill: Dict[str, int] = {}
        for i in todo:
            n = int(vals[i].size)
            dt = str(vals[i].dtype)
            if n >= bound:
                buckets.append([i])
                continue
            if dt not in cur or fill[dt] + n > bound:
                cur[dt] = []
                buckets.append(cur[dt])
                fill[dt] = 0
            cur[dt].append(i)
            fill[dt] += n
        ctype = (self._compression or {}).get("type")
        if priorities is not None and len(buckets) > 1:
            # dispatch order: highest priority first, stable on the
            # original bucket sequence — deterministic across ranks
            order = sorted(range(len(buckets)),
                           key=lambda bi: (-max(priorities[i]
                                                for i in buckets[bi]),
                                           bi))
            buckets = [buckets[bi] for bi in order]
        for idxs in buckets:
            arrs = [jnp.asarray(vals[i]._data) for i in idxs]
            flat = arrs[0].ravel() if len(arrs) == 1 else \
                jnp.concatenate([a.ravel() for a in arrs])
            t0 = time.perf_counter()
            wire0 = self.reduce_wire_bytes
            if ctype:
                segs = [(keys[i], int(vals[i].size)) for i in idxs]
                red = self._reduce_flat_compressed(flat, ctype, segs)
            else:
                red = self._reduce_flat(flat)
            # compressed-vs-raw wire accounting (the EQuARX win, per
            # codec): raw is what an uncompressed reduce would have
            # gathered, compressed is what this one actually did
            KV_RAW_BYTES.labels(ctype=ctype or "none").inc(
                int(flat.size) * flat.dtype.itemsize)
            KV_COMPRESSED_BYTES.labels(ctype=ctype or "none").inc(
                self.reduce_wire_bytes - wire0)
            self.reduce_collectives += 1
            _metrics.COLLECTIVE_CALLS.labels(
                collective="allreduce", traced="0").inc()
            _metrics.COLLECTIVE_BYTES.labels(
                collective="allreduce", traced="0").inc(
                int(flat.size) * flat.dtype.itemsize)
            _metrics.COLLECTIVE_SECONDS.labels(
                collective="allreduce").observe(time.perf_counter() - t0)
            off = 0
            for i, a in zip(idxs, arrs):
                piece = red[off:off + a.size].reshape(a.shape)
                off += a.size
                data = vals[i]._data
                o = NDArray(piece, ctx=vals[i].context)
                # preserve the input's placement: a local-mesh-replicated
                # gradient must come back with the same sharding so the
                # following optimizer op doesn't mix devices
                o._data = jax.device_put(o._data, data.sharding)
                out[i] = o
        return out  # type: ignore[return-value]

    def _reduce_flat(self, flat):
        """Sum a flat per-process contribution across all processes.

        Preferred path: ONE compiled SPMD program over the global device
        mesh — each process contributes its row of a (W, n) array sharded
        over the process axis; XLA lowers the sum to an all-reduce riding
        ICI/DCN and every participant receives the identical replicated
        result. Fallback (no global mesh): ``process_allgather`` +
        fixed-order host sum.

        The path is chosen ONCE, by a tiny capability probe on the first
        reduction — never per call: a per-call try/except would let one
        rank fall back while its peers sit inside the mesh collective,
        deadlocking the job on mismatched collective sequences. A probe
        failure is a deterministic property of the environment (missing
        API, unbuildable mesh), so every rank reaches the same verdict."""
        key = ("sum", int(flat.shape[0]), str(flat.dtype))
        return self._gather_decode_sum(
            (flat,), lambda g: jnp.sum(g, axis=0), key).astype(flat.dtype)

    def _reduce_flat_compressed(self, flat, ctype: str, segs) -> Any:
        """Cross-process sum of ``flat`` through a lossy compressed
        collective: each process quantizes/packs its contribution, only
        the packed payload crosses the wire (allgather), and the decode +
        f32 sum run inside one compiled program on every participant
        (EQuARX-style quantized collective — SURVEY.md 5.8's TPU mapping
        of gradient_compression.cc). ``segs`` is the bucket's [(key,
        size), ...] layout — 2-bit error-feedback residuals are stored
        PER KEY, so deferred gradient mass survives changes in bucket
        composition between pushes."""
        n = int(flat.shape[0])
        if ctype in ("fp16", "bf16"):
            dt = jnp.float16 if ctype == "fp16" else jnp.bfloat16
            red = self._gather_decode_sum(
                (flat.astype(dt),),
                lambda g: jnp.sum(g.astype(jnp.float32), axis=0),
                (ctype, n))
            return red.astype(flat.dtype)
        if ctype == "int8":
            codes, scales, _ = _quantize_int8(flat)

            def decode_i8(c, s):
                W = c.shape[0]
                vals = (c.reshape(W, -1, _INT8_BLOCK).astype(jnp.float32)
                        * s[:, :, None]).reshape(W, -1)[:, :n]
                return jnp.sum(vals, axis=0)

            red = self._gather_decode_sum((codes, scales), decode_i8,
                                          ("int8", n))
            return red.astype(flat.dtype)
        # 2bit: error-feedback residual held locally PER KEY, so what
        # the quantizer drops this step is re-offered next step even if
        # the key lands in a differently-composed bucket
        thr = float(self._compression.get("threshold", 0.5))
        res_parts = []
        for k, sz in segs:
            r = self._ici_residuals.get(k)
            if r is None or int(r.shape[0]) != sz:
                r = jnp.zeros(sz, jnp.float32)
            res_parts.append(r)
        res = res_parts[0] if len(res_parts) == 1 \
            else jnp.concatenate(res_parts)
        acc = flat.astype(jnp.float32) + res
        packed, deq = _quantize_2bit(acc, thr)
        newres = acc - deq.astype(jnp.float32)
        off = 0
        for k, sz in segs:
            self._ici_residuals[k] = newres[off:off + sz]
            off += sz

        def decode_2bit(p):
            W = p.shape[0]
            parts = [(p >> s) & 3 for s in (0, 2, 4, 6)]
            codes = jnp.stack(parts, axis=2).reshape(W, -1)[:, :n]
            return jnp.sum((codes.astype(jnp.float32) - 1.0) * thr, axis=0)

        red = self._gather_decode_sum((packed,), decode_2bit,
                                      ("2bit", n, thr))
        return red.astype(flat.dtype)

    def _gather_decode_sum(self, payloads, decode, cache_key):
        """Allgather each per-process flat payload into a (W, n_i) row
        stack and return ``decode(*stacks)`` — computed identically on
        every process. Preferred path: ONE compiled SPMD program over the
        global device mesh (payload rows sharded over the process axis,
        replicated output — XLA lowers the gather to collectives riding
        ICI/DCN). Fallback: ``process_allgather`` + host decode.

        The path is chosen ONCE by a capability probe — never per call: a
        per-call try/except could let one rank fall back while its peers
        sit inside the mesh collective, deadlocking the job on mismatched
        collective sequences. A probe failure is a deterministic property
        of the environment, so every rank reaches the same verdict."""
        t0 = time.perf_counter()
        for p in payloads:
            nbytes = int(p.size) * p.dtype.itemsize
            self.reduce_wire_bytes += nbytes
            _metrics.COLLECTIVE_BYTES.labels(
                collective="allgather", traced="0").inc(nbytes)
        _metrics.COLLECTIVE_CALLS.labels(
            collective="allgather", traced="0").inc()
        try:
            return self._gather_decode_sum_impl(payloads, decode,
                                                cache_key)
        finally:
            _metrics.COLLECTIVE_SECONDS.labels(
                collective="allgather").observe(time.perf_counter() - t0)

    def _gather_decode_sum_impl(self, payloads, decode, cache_key):
        from jax.experimental import multihost_utils
        if self._use_mesh_reduce is None:
            try:
                self._mesh_probe()
                self._use_mesh_reduce = True
            except Exception:
                self._use_mesh_reduce = False
        if not self._use_mesh_reduce:
            gathered = [jnp.asarray(multihost_utils.process_allgather(p))
                        for p in payloads]
            return decode(*gathered)
        mesh = self._ensure_mesh()
        P = jax.sharding.PartitionSpec
        prog = self._reduce_progs.get(cache_key)
        if prog is None:
            prog = jax.jit(
                decode,
                out_shardings=jax.sharding.NamedSharding(mesh, P()))
            self._reduce_progs[cache_key] = prog
        garrs = [multihost_utils.host_local_array_to_global_array(
            p[None, :], mesh, P("w")) for p in payloads]
        return prog(*garrs).addressable_data(0)

    def _ensure_mesh(self):
        import numpy as onp
        if self._reduce_mesh is None:
            devs = sorted(jax.devices(),
                          key=lambda d: (d.process_index, d.id))
            W = jax.process_count()
            self._reduce_mesh = jax.sharding.Mesh(
                onp.array(devs).reshape(W, len(devs) // W), ("w", "l"))
        return self._reduce_mesh

    def _mesh_probe(self):
        from jax.experimental import multihost_utils
        P = jax.sharding.PartitionSpec
        mesh = self._ensure_mesh()
        probe = jax.jit(
            lambda g: jnp.sum(g, axis=0),
            out_shardings=jax.sharding.NamedSharding(mesh, P()))
        garr = multihost_utils.host_local_array_to_global_array(
            jnp.zeros((1, 8), jnp.float32), mesh, P("w"))
        probe(garr).addressable_data(0)

    @property
    def rank(self) -> int:
        return jax.process_index()

    @property
    def num_workers(self) -> int:
        return jax.process_count()


def create(name: str = "local") -> KVStore:
    """Create a KVStore (``mx.kv.create``). See module docstring for the
    type mapping from the reference."""
    name = (name or "local").lower()
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device", "nccl"):
        return KVStore(name)
    if name in ("ici", "dist", "dist_sync", "dist_device_sync",
                "dist_sync_device", "horovod"):
        return KVStoreICI(name)
    if name == "dist_async":
        # the host-driven DCN parameter service (SURVEY.md 5.8): workers
        # push/pull over TCP to server processes that apply the optimizer
        # immediately per push. Requires the launcher's env contract.
        import os as _os
        if int(_os.environ.get("DMLC_NUM_SERVER", "0") or 0) < 1:
            raise MXNetError(
                "kvstore='dist_async' is the host-side parameter service "
                "— launch the job with server processes, e.g. "
                "`python tools/launch.py -n 2 -s 1 python train.py` "
                "(ICI collectives themselves are synchronous by "
                "construction; use 'ici' for sync data parallel)")
        from .kvstore_async import KVStoreDistAsync
        return KVStoreDistAsync()
    raise MXNetError(f"unknown kvstore type {name!r}")
