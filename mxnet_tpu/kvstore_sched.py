"""Gradient-reduction scheduler — bucketed, priority-ordered, overlapped.

Reference parity (leezu/mxnet): the dependency engine's prioritized
PushAsync of kvstore ops (``priority=-param_index`` from
``gluon/trainer.py``) + the "Efficient Embedding of MPI Collectives in
MXNet DAGs" scheduling idea (PAPERS.md) — launch gradient reductions as
buckets become ready, ordered so the parameters the next forward needs
first arrive first, and run the wire concurrently with the remaining
backward/optimizer compute so step time approaches ``max(compute,
comm)`` instead of their sum.

Design (tpu-first):

* **Buckets** — the submitted (key, grad) list is cut into byte-budgeted
  buckets (``MXNET_KV_BUCKET_BYTES``) **in registration order, never by
  arrival timing**: composition is a pure function of (keys, sizes,
  budget), so the per-key 2-bit error-feedback residuals in
  ``kvstore.py``/``kvstore_async.py`` see the same per-key payload
  sequence no matter how the schedule interleaves, and every SPMD rank
  computes the identical bucket list with no metadata exchange.
* **Priority + readiness** — each bucket's priority is the max of its
  members' (the gluon Trainer passes ``-param_index``; see
  ``KVStore.push``).  The comm thread pops the highest-priority bucket
  whose payload is already materialized (``jax.Array.is_ready`` — a
  non-blocking probe): reductions launch as backward produces their
  gradients (reverse parameter order), overlapping the wire with the
  REMAINING backward compute, while priority decides contention so
  first-needed parameters cross the wire first.  Rounds marked
  ``strict_order`` (multi-process 'ici' stores, where every rank must
  issue the same collective sequence) disable the readiness probe and
  pop in pure priority order.
* **One comm thread** — a process-wide daemon thread runs the actual
  reductions (``reduce_fn`` per bucket: kvstore push + pull).  It is
  armed with the PR-5 hang watchdog under the named stall site
  ``kvstore.bucket``; the main thread's per-bucket wait arms the same
  site with ``side=wait``.  All blocking work (collectives, sockets,
  the synthetic wire) happens OUTSIDE the scheduler lock (mxlint
  MX-L001 is a tier-1 gate on this file).
* **Event-driven streaming (beside the poll)** — :func:`open_round`
  plans a round whose buckets start un-queued; the gluon Trainer's
  grad-ready hooks (``Parameter._grad_ready_cb``, fired by backward
  the moment a parameter's gradient finalizes) ``Round.offer`` keys,
  and a bucket seals + dispatches when its last key arrives.  With
  per-layer backward segmentation (``MXNET_BULK_BACKWARD_SEGMENTS=
  param``) gradients finalize in reverse registration order WHILE
  backward still runs, so buckets hit the wire during backward itself
  — the readiness probe then still gates actual dispatch (a sealed
  bucket whose payload is an in-flight pullback is not popped until
  it materializes).  ``Round.seal_remaining`` at step time enqueues
  whatever never streamed.
* **Per-bucket blocking** — ``Round.wait`` blocks only on one bucket,
  so the optimizer update for a parameter starts as soon as *its*
  bucket arrives while later buckets are still on the wire
  (``gluon/trainer.py _update`` consumes ``Round.as_completed`` —
  arrival order — for functional optimizers, and falls back to
  registration-order waits for order-sensitive ones).

Determinism contract for SPMD ('ici') stores: a round's buckets are
enqueued atomically and drained before the trainer's step returns, so
the comm thread issues the round's collectives in pure priority order —
identical on every rank.  Two *concurrent* training loops in different
host threads of the same process would interleave rounds
non-deterministically across ranks; keep one driving thread per process
for multi-host collectives (the same rule the rest of the stack
follows).

Metrics: ``mxnet_kv_buckets_total``, ``mxnet_kv_bucket_seconds`` (comm-
thread latency per bucket), ``mxnet_kv_bucket_wait_seconds`` (the
exposed, non-overlapped stall per wait), and ``mxnet_kv_overlap_fraction``
(per round: the share of comm time hidden under compute).  The
compressed-vs-raw byte families live with the encoders
(``kvstore.py``/``kvstore_async.py``).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from . import metrics as _metrics
from . import tracing as _tracing
from .base import MXNetError, getenv, register_env

__all__ = ["Bucket", "Round", "submit", "open_round", "plan_buckets"]

register_env(
    "MXNET_KV_BUCKET_BYTES", 4 << 20,
    "Byte budget of one scheduled gradient-reduction bucket: the "
    "overlapped kvstore scheduler (kvstore_sched.py) cuts the pushed "
    "key list into buckets of up to this many raw gradient bytes, in "
    "registration order, and reduces each bucket as one unit on the "
    "comm thread.  Smaller buckets start the wire earlier and pipeline "
    "more; larger buckets amortize per-collective/per-frame overhead.")

register_env(
    "MXNET_KV_OVERLAP", 1,
    "Overlapped gradient reduction: 1 (default) routes gluon.Trainer "
    "gradient pushes through the bucketed, priority-scheduled comm "
    "thread so wire time hides under backward/optimizer compute "
    "(mxnet_kv_overlap_fraction shows how much).  Engages only when "
    "the store has an actual wire to hide — a multi-process "
    "collective store, the dist_async parameter service, or "
    "MXNET_KV_SYNTH_WIRE_GBPS > 0; a single-process local store's "
    "no-op reduction never pays the comm-thread handoff.  0 forces "
    "the serialized push-all/pull-all path everywhere.")

register_env(
    "MXNET_KV_BACKWARD_STREAM", 1,
    "Event-driven gradient streaming: 1 (default) lets the gluon "
    "Trainer open its reduction round BEFORE backward and submit "
    "buckets from grad-ready hooks as each parameter's gradient "
    "finalizes, so with per-layer backward segmentation "
    "(MXNET_BULK_BACKWARD_SEGMENTS=param) wire time hides under "
    "backward itself, not just under the optimizer update.  Engages "
    "only where the PR-14 scheduler would (a real wire, worker-side "
    "updates, non-strict collective order) and never with gradient "
    "compression — lossy codecs mutate per-key error-feedback "
    "residuals at push, and a discarded streamed round must be free "
    "of side effects, so compressed trainers keep the step-time "
    "submission (optimizer-phase overlap).  Reduced values land in a "
    "per-key staging buffer and are absorbed at step time, so "
    "gradients a second backward() accumulates before step() are "
    "never overwritten mid-flight (such rounds are discarded and "
    "re-reduced post-backward).  0 keeps the round submission at "
    "step time (optimizer-phase overlap only).")

register_env(
    "MXNET_KV_SYNTH_WIRE_GBPS", 0.0,
    "Synthetic-slow-wire calibration knob for the single-process "
    "kvstore ('local'/'device'/'ici'): when > 0, every KVStore.push "
    "first blocks until its payload is materialized (a real wire "
    "cannot transmit an unmaterialized gradient) and then sleeps "
    "raw_bytes / (GBps * 1e9) seconds, modeling a wire of that many "
    "gigaBYTES/sec.  Both the serialized and the overlapped reduction "
    "paths pay the identical simulated wire time, which is what makes "
    "the dist-comm-smoke overlap ratio a fair measurement.  0 "
    "(default) disables it.  The dist_async store is unaffected (its "
    "TCP wire is real).")

KV_BUCKETS = _metrics.counter(
    "mxnet_kv_buckets_total",
    "Gradient buckets dispatched by the overlapped reduction scheduler "
    "(kvstore_sched.py).")
KV_BUCKET_SECONDS = _metrics.histogram(
    "mxnet_kv_bucket_seconds",
    "Comm-thread wall time of one scheduled gradient-bucket reduction "
    "(kvstore push + pull, including any synthetic wire delay).")
KV_BUCKET_WAIT_SECONDS = _metrics.histogram(
    "mxnet_kv_bucket_wait_seconds",
    "Main-thread time blocked waiting for a scheduled gradient bucket "
    "that had not finished reducing — the NON-overlapped share of comm "
    "time (0 means the bucket arrived before the optimizer needed it).")
KV_OVERLAP_FRACTION = _metrics.gauge(
    "mxnet_kv_overlap_fraction",
    "Per reduction round: 1 - (main-thread bucket wait / comm-thread "
    "busy time), clamped to [0, 1] — the share of communication the "
    "schedule hid under compute.  ~1 means the wire is fully hidden; "
    "~0 means the round ran serialized.")
KV_PHASE_OVERLAP_FRACTION = _metrics.gauge(
    "mxnet_kv_phase_overlap_fraction",
    "The per-round overlap split by WHERE the wire hid, phase="
    "'backward' (comm-thread busy time that completed before the "
    "trainer first BLOCKED on the round — i.e. concurrent with "
    "backward's host walk and device tail; only the event-driven "
    "streaming path, fed by per-layer backward segmentation, can make "
    "this nonzero) vs 'optimizer' (comm hidden under the per-bucket "
    "optimizer updates, the PR-14 overlap).  Fractions of the round's "
    "total comm time; their sum plus the exposed fraction "
    "(mxnet_kv_bucket_wait_seconds) is ~1.", labels=("phase",))
KV_STREAM_ENQUEUES = _metrics.counter(
    "mxnet_kv_stream_enqueues_total",
    "Reduction buckets sealed and handed to the comm thread by the "
    "event-driven path (Round.offer from a grad-ready hook) BEFORE the "
    "trainer's step consumed the round — buckets whose wire time could "
    "start under backward itself.")

_QUEUED, _RUNNING, _DONE, _CANCELLED, _PLANNED = range(5)

# polls of an all-unready queue before the scheduler gives up on the
# readiness probe and head-of-line-blocks on the best bucket anyway (a
# value that never reports ready — e.g. an exotic buffer type — must
# not livelock the comm thread; push forces materialization regardless)
_READY_POLL_CAP = 100
_READY_POLL_S = 0.0005


def _bucket_ready(bucket: "Bucket") -> bool:
    """Non-blocking: is every value of this bucket materialized on
    device?  A pending bulked segment or an in-flight jax future is
    not; forcing would serialize exactly the compute the schedule is
    hiding, so the probe only ever peeks."""
    from .bulk import PendingBuffer
    for v in bucket.vals:
        buf = getattr(v, "_buf", None)
        if buf is None:
            continue
        if type(buf) is PendingBuffer:
            if buf.value is None:
                return False
            buf = buf.value
        is_ready = getattr(buf, "is_ready", None)
        try:
            if is_ready is not None and not is_ready():
                return False
        except Exception:   # noqa: BLE001 - deleted/donated: push decides
            pass
    return True


class Bucket:
    """One scheduled reduction unit: a registration-order-contiguous
    slice of the submitted keys, at most ``MXNET_KV_BUCKET_BYTES`` of
    raw gradient payload (a single oversized gradient gets a bucket of
    its own)."""

    __slots__ = ("bid", "keys", "vals", "priority", "nbytes", "state",
                 "error", "ctx", "round")

    def __init__(self, bid: int, keys: List[Any], vals: List[Any],
                 priority: int, nbytes: int) -> None:
        self.bid = bid
        self.keys = keys
        self.vals = vals
        self.priority = priority
        self.nbytes = nbytes
        self.state = _QUEUED
        self.error: Optional[BaseException] = None
        self.ctx: Dict[str, Any] = {}   # per-bucket scratch (e.g. the
        #                                 dist_async pre-reserved seqs)
        self.round: Optional["Round"] = None


def plan_buckets(keys: Sequence[Any], vals: Sequence[Any],
                 priorities: Sequence[int],
                 bucket_bytes: Optional[int] = None) -> List[Bucket]:
    """Cut (keys, vals) into byte-budgeted buckets in the given
    (registration) order.  Pure: composition depends only on the key
    order, the per-value raw byte sizes, and the budget — never on
    priorities (they order *dispatch*, not membership) and never on
    arrival timing."""
    if bucket_bytes is None:
        bucket_bytes = int(getenv("MXNET_KV_BUCKET_BYTES", 4 << 20))
    bucket_bytes = max(1, int(bucket_bytes))
    buckets: List[Bucket] = []
    cur_k: List[Any] = []
    cur_v: List[Any] = []
    cur_p: List[int] = []
    fill = 0

    def close() -> None:
        nonlocal cur_k, cur_v, cur_p, fill
        if cur_k:
            buckets.append(Bucket(len(buckets), cur_k, cur_v,
                                  max(cur_p), fill))
            cur_k, cur_v, cur_p, fill = [], [], [], 0

    for k, v, p in zip(keys, vals, priorities):
        try:
            nbytes = int(v.size) * int(getattr(v.dtype, "itemsize", 4))
        except Exception:   # noqa: BLE001 - sizeless value: count as 1
            nbytes = 1
        if cur_k and fill + nbytes > bucket_bytes:
            close()
        cur_k.append(k)
        cur_v.append(v)
        cur_p.append(int(p))
        fill += nbytes
        if fill >= bucket_bytes:
            close()
    close()
    return buckets


class Round:
    """One submitted reduction round: the bucket list plus completion
    tracking.  Created by :func:`submit`; the caller waits buckets
    (usually in registration order) and must :meth:`finish` when done —
    ``finish`` cancels still-queued buckets on error paths, drains any
    in-flight bucket, re-raises the first unconsumed error, and
    publishes the round's overlap fraction."""

    def __init__(self, buckets: List[Bucket],
                 streaming: bool = False) -> None:
        self.buckets = buckets
        self._by_key: Dict[Any, Bucket] = {}
        for b in buckets:
            b.round = self
            for k in b.keys:
                self._by_key[k] = b
        self.comm_seconds = 0.0     # comm-thread busy time (all buckets)
        self.comm_backward_seconds = 0.0  # ...accrued during backward
        self.wait_seconds = 0.0     # main-thread exposed stalls
        self._finished = False
        # streaming (event-driven) rounds: buckets start PLANNED and are
        # sealed one by one as grad-ready hooks offer their keys — see
        # open_round
        self._streaming = streaming
        self._reduce_fn: Optional[Callable] = None
        self._prepare_fn: Optional[Callable] = None
        self._strict = False
        self._backward_done = not streaming
        self._pending: Dict[int, set] = {}
        if streaming:
            for b in buckets:
                b.state = _PLANNED
                self._pending[b.bid] = set(b.keys)

    @property
    def planned_keys(self) -> List[Any]:
        return list(self._by_key)

    def mark_backward_end(self) -> None:
        """The driving thread is about to BLOCK on this round (first
        ``wait``/``as_completed`` — the consumption phase): comm-thread
        busy time from here on counts as optimizer-phase.  Everything
        before ran concurrently with backward's host walk and device
        tail, i.e. was hidden under backward — the backward-phase
        share of the overlap-split gauges."""
        self._backward_done = True

    def offer(self, key: Any) -> bool:
        """Event-driven enqueue (grad-ready hook -> here): mark ``key``
        ready; when the last key of its bucket arrives the bucket is
        sealed — prepare_fn runs on THIS thread, then the bucket joins
        the comm queue, dispatching while backward still runs.

        Returns False when the key's value may already be on the wire
        (its bucket was sealed before this offer — a SECOND backward
        wrote the grad after the first one streamed it); the trainer
        treats that as a dirty round and falls back to a fresh
        post-backward reduction of the accumulated gradients."""
        b = self._by_key.get(key)
        if b is None:
            return False
        pend = self._pending.get(b.bid)
        if pend is None or key not in pend:
            # re-offer: benign while the bucket is still unsealed (the
            # push will read the latest value), dirty once sealed
            return b.state == _PLANNED
        pend.discard(key)
        if not pend:
            del self._pending[b.bid]
            self._seal(b, streamed=True)
        return True

    def _seal(self, bucket: Bucket, streamed: bool = False) -> None:
        if self._prepare_fn is not None:
            self._prepare_fn(bucket)
        if streamed:
            KV_STREAM_ENQUEUES.inc()
        _scheduler().enqueue_bucket(bucket, self._reduce_fn,
                                    self._strict)

    def seal_remaining(self, eligible: Optional[set] = None) -> None:
        """Enqueue every still-planned bucket in registration order
        (the trainer calls this at step time for keys whose grad-ready
        hooks never fired).  ``eligible`` filters keys that turned out
        not to participate (a gradient that materialized row_sparse);
        a bucket left empty completes immediately."""
        for b in self.buckets:
            if b.state != _PLANNED:
                continue
            self._pending.pop(b.bid, None)
            if eligible is not None and not set(b.keys) <= eligible:
                keep = [(k, v) for k, v in zip(b.keys, b.vals)
                        if k in eligible]
                b.keys = [k for k, _ in keep]
                b.vals = [v for _, v in keep]
                if not b.keys:
                    with _scheduler().cv:
                        b.state = _DONE
                    continue
            self._seal(b)

    def bucket_of(self, key: Any) -> Optional[Bucket]:
        return self._by_key.get(key)

    def wait(self, bucket: Bucket) -> None:
        """Block until ``bucket`` finished reducing; re-raise its
        error on this (the caller's) thread."""
        self.mark_backward_end()        # consumption phase begins
        if bucket.state == _DONE and bucket.error is None:
            return
        from . import health as _health
        t0 = time.perf_counter()
        sched = _scheduler()
        with _tracing.child_span("bucket.wait", bucket=bucket.bid), \
                _health.watch_section("kvstore.bucket", side="wait",
                                      bucket=bucket.bid):
            with sched.cv:
                while bucket.state not in (_DONE, _CANCELLED):
                    sched.cv.wait()
        waited = time.perf_counter() - t0
        KV_BUCKET_WAIT_SECONDS.observe(waited)
        self.wait_seconds += waited
        if bucket.error is not None:
            err, bucket.error = bucket.error, None   # raise exactly once
            raise err
        if bucket.state == _CANCELLED:
            raise MXNetError(
                f"gradient bucket {bucket.bid} was cancelled before it "
                "reduced (an earlier bucket in the round failed)")

    def wait_key(self, key: Any) -> None:
        b = self._by_key.get(key)
        if b is not None:
            self.wait(b)

    def as_completed(self):
        """Yield this round's buckets as they finish reducing — the
        consumption order that maximizes overlap (the caller updates
        whichever parameters arrived first while later buckets are
        still on the wire).  Only valid for per-parameter-independent
        consumers; order-sensitive ones (optimizers with eager
        global-RNG noise) should walk ``buckets`` with :meth:`wait`
        instead.  Errors re-raise at the failing bucket's yield turn."""
        self.mark_backward_end()        # consumption phase begins
        remaining = list(self.buckets)
        sched = _scheduler()
        while remaining:
            t0 = time.perf_counter()
            with sched.cv:
                while True:
                    done = [b for b in remaining
                            if b.state in (_DONE, _CANCELLED)]
                    if done:
                        break
                    sched.cv.wait()
            waited = time.perf_counter() - t0
            KV_BUCKET_WAIT_SECONDS.observe(waited)
            self.wait_seconds += waited
            for b in done:
                remaining.remove(b)
                if b.error is not None:
                    err, b.error = b.error, None
                    raise err
                if b.state == _CANCELLED:
                    raise MXNetError(
                        f"gradient bucket {b.bid} was cancelled before "
                        "it reduced (an earlier bucket in the round "
                        "failed)")
                yield b

    def finish(self) -> None:
        """Drain the round: cancel queued buckets, wait out a running
        one, publish overlap metrics, re-raise the first unconsumed
        error.  Idempotent.  On a cleanup path where another exception
        is already propagating, use :meth:`abort` instead — raising
        here would replace the primary error."""
        if self._drain():
            return
        for b in self.buckets:
            if b.error is not None:
                err, b.error = b.error, None
                raise err

    def abort(self) -> None:
        """The never-raising :meth:`finish`: drain the round and LOG
        (not raise) unconsumed bucket errors.  For except/finally
        blocks where a primary exception is already on its way to the
        caller and a secondary reduce error must not mask it."""
        if self._drain():
            return
        for b in self.buckets:
            if b.error is not None:
                err, b.error = b.error, None
                import logging
                logging.getLogger("mxnet_tpu.kvstore_sched").error(
                    "gradient bucket %d failed during an aborted "
                    "round (suppressed behind the primary error): %s",
                    b.bid, err)

    def _drain(self) -> bool:
        """Cancel queued buckets, wait out running ones, publish the
        round's overlap fraction.  Returns True when already done."""
        if self._finished:
            return True
        self._finished = True
        sched = _scheduler()
        with sched.cv:
            for b in self.buckets:
                if b.state in (_QUEUED, _PLANNED):
                    b.state = _CANCELLED
            while any(b.state == _RUNNING for b in self.buckets):
                sched.cv.wait()
        if self.comm_seconds > 0:
            frac = 1.0 - min(self.wait_seconds / self.comm_seconds, 1.0)
            KV_OVERLAP_FRACTION.set(max(0.0, frac))
            # the phase split: comm that ran under backward is hidden by
            # construction; the optimizer-phase share is whatever else
            # was hidden (total comm - backward comm - exposed wait)
            bwd = min(self.comm_backward_seconds, self.comm_seconds)
            opt = max(0.0, self.comm_seconds - bwd - self.wait_seconds)
            KV_PHASE_OVERLAP_FRACTION.labels(phase="backward").set(
                bwd / self.comm_seconds)
            KV_PHASE_OVERLAP_FRACTION.labels(phase="optimizer").set(
                opt / self.comm_seconds)
        return False


class _Scheduler:
    """The process-wide comm thread + priority queue.  One instance;
    rounds from any trainer share it (each round drains before its
    trainer's step returns, so rounds never interleave per driving
    thread)."""

    def __init__(self) -> None:
        self.cv = threading.Condition()
        self._queue: List[Any] = []       # (neg_priority, seq, bucket)
        self._seq = 0
        self._thread: Optional[threading.Thread] = None

    def enqueue_round(self, rnd: Round, reduce_fn: Callable,
                      strict_order: bool) -> None:
        """Atomically queue every bucket of a round (the comm thread
        only ever sees the complete round, so its pops are a
        deterministic function of priorities and — unless
        ``strict_order`` — payload readiness)."""
        # the enqueuing (trainer) thread's trace context rides in the
        # bucket's ctx scratch: the comm thread re-attaches it so the
        # wire span lands in the training step's trace
        tr = _tracing.capture()
        with self.cv:
            for b in rnd.buckets:
                self._seq += 1
                b.ctx["_reduce_fn"] = reduce_fn
                b.ctx["strict"] = strict_order
                b.ctx["trace"] = tr
                b.ctx["t_enq"] = time.perf_counter()
                self._queue.append((-b.priority, self._seq, b))
            self._queue.sort()
            self._ensure_thread()
            self.cv.notify_all()

    def enqueue_bucket(self, bucket: Bucket, reduce_fn: Callable,
                       strict_order: bool) -> None:
        """The event-driven enqueue path: one sealed bucket of a
        streaming round joins the queue immediately (Round.offer calls
        this from the grad-ready hook, i.e. from inside backward), so
        its reduction can dispatch while the rest of backward is still
        producing gradients.  Never used with ``strict_order`` rounds —
        seal order is readiness timing, which differs per rank."""
        with self.cv:
            self._seq += 1
            bucket.ctx["_reduce_fn"] = reduce_fn
            bucket.ctx["strict"] = strict_order
            # sealed from a grad-ready hook during backward: whatever
            # trace is active on the offering thread (none, when
            # backward runs outside a step span) parents the wire span
            bucket.ctx["trace"] = _tracing.capture()
            bucket.ctx["t_enq"] = time.perf_counter()
            bucket.state = _QUEUED
            self._queue.append((-bucket.priority, self._seq, bucket))
            self._queue.sort()
            self._ensure_thread()
            self.cv.notify_all()

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="mxnet-kv-comm", daemon=True)
            self._thread.start()

    def _pop_locked(self, ignore_ready: bool) -> Optional[Bucket]:
        """Highest-priority queued bucket, readiness-filtered unless
        the queue is strict (SPMD rounds need pure priority order on
        every rank) or ``ignore_ready`` (poll cap hit).  The queue is
        priority-sorted; the scan stops at the first viable entry.
        The readiness probe runs under the scheduler lock but is
        non-blocking by construction (``is_ready`` peeks)."""
        for ent in self._queue:
            b = ent[2]
            if b.state != _QUEUED:
                continue
            if ignore_ready or b.ctx.get("strict") \
                    or _bucket_ready(b):
                self._queue.remove(ent)
                return b
        return None

    def _loop(self) -> None:
        polls = 0
        while True:
            with self.cv:
                while True:
                    bucket = self._pop_locked(polls >= _READY_POLL_CAP)
                    if bucket is not None:
                        bucket.state = _RUNNING
                        polls = 0
                        break
                    has_queued = any(e[2].state == _QUEUED
                                     for e in self._queue)
                    if not has_queued:
                        polls = 0
                        self._queue = [e for e in self._queue
                                       if e[2].state == _QUEUED]
                        self.cv.wait()
                    else:
                        # something is queued but nothing is ready yet:
                        # poll — backward is still producing the
                        # payloads, and there is no notification hook
                        # on device-side completion
                        polls += 1
                        self.cv.wait(timeout=_READY_POLL_S)
            self._run(bucket)

    def _run(self, bucket: Bucket) -> None:
        from . import health as _health
        reduce_fn = bucket.ctx.pop("_reduce_fn")
        tr = bucket.ctx.pop("trace", None)
        t_enq = bucket.ctx.pop("t_enq", None)
        t0 = time.perf_counter()
        if t_enq is not None:
            # queue time: seal/enqueue -> comm-thread pop
            _tracing.record_span("bucket.dispatch", t_enq, t0, ctx=tr,
                                 bucket=bucket.bid)
        try:
            with _tracing.attach(tr), \
                    _tracing.child_span("bucket.wire",
                                        bucket=bucket.bid,
                                        keys=len(bucket.keys),
                                        nbytes=bucket.nbytes), \
                    _health.watch_section("kvstore.bucket",
                                          bucket=bucket.bid,
                                          keys=len(bucket.keys),
                                          nbytes=bucket.nbytes):
                reduce_fn(bucket)
        except BaseException as exc:   # noqa: BLE001 - handed to waiter
            bucket.error = exc
        finally:
            dt = time.perf_counter() - t0
            KV_BUCKETS.inc()
            KV_BUCKET_SECONDS.observe(dt)
            rnd = bucket.round
            if rnd is not None:
                rnd.comm_seconds += dt
                if not rnd._backward_done:
                    rnd.comm_backward_seconds += dt
            with self.cv:
                bucket.state = _DONE
                self.cv.notify_all()


_SCHED_LOCK = threading.Lock()
_SCHED: Optional[_Scheduler] = None


def _scheduler() -> _Scheduler:
    global _SCHED
    s = _SCHED
    if s is None:
        with _SCHED_LOCK:
            s = _SCHED
            if s is None:
                s = _SCHED = _Scheduler()
    return s


def submit(keys: Sequence[Any], vals: Sequence[Any],
           priorities: Sequence[int],
           reduce_fn: Callable[[Bucket], None],
           prepare_fn: Optional[Callable[[Bucket], None]] = None,
           bucket_bytes: Optional[int] = None,
           strict_order: bool = False) -> Round:
    """Plan buckets over (keys, vals) and hand them to the comm thread.

    ``reduce_fn(bucket)`` runs on the comm thread, once per bucket, in
    descending-priority order among READY buckets (pure priority order
    with ``strict_order`` — required for multi-process 'ici' stores,
    where every rank must issue the identical collective sequence);
    ``prepare_fn(bucket)`` (optional) runs synchronously HERE, on the
    caller's thread, in registration order before anything is queued —
    the hook where the dist_async client reserves its exactly-once
    push seqs at enqueue time, so pipelined (and retried) sends replay
    safely no matter when the comm thread gets to them."""
    rnd = Round(plan_buckets(keys, vals, priorities, bucket_bytes))
    if prepare_fn is not None:
        for b in rnd.buckets:
            prepare_fn(b)
    _scheduler().enqueue_round(rnd, reduce_fn, strict_order)
    return rnd


def open_round(keys: Sequence[Any], vals: Sequence[Any],
               priorities: Sequence[int],
               reduce_fn: Callable[[Bucket], None],
               prepare_fn: Optional[Callable[[Bucket], None]] = None,
               bucket_bytes: Optional[int] = None) -> Round:
    """Plan a STREAMING round: buckets are composed exactly as
    :func:`submit` would (pure function of registration order + sizes,
    so 2-bit error-feedback residual determinism survives) but start
    un-queued.  The caller's grad-ready hooks :meth:`Round.offer` keys
    as backward finalizes their gradients; each bucket seals — and its
    reduction dispatches — the moment its last key arrives, which in
    reverse-registration backward order means buckets stream onto the
    wire DURING backward.  :meth:`Round.seal_remaining` at step time
    enqueues whatever never streamed; from there the round is consumed
    like any other (``wait``/``as_completed``/``finish``).  Never
    strict-order: multi-process collective stores need rank-identical
    dispatch sequences, which seal timing is not — callers keep those
    on :func:`submit`."""
    rnd = Round(plan_buckets(keys, vals, priorities, bucket_bytes),
                streaming=True)
    rnd._reduce_fn = reduce_fn
    rnd._prepare_fn = prepare_fn
    return rnd
