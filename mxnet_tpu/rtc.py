"""Runtime-compiled user kernels — the ``mx.rtc`` analog on TPU.

Reference parity (leezu/mxnet): ``src/common/rtc.cc`` (``CudaModule``) —
users hand NVRTC a CUDA C source string at runtime, get back callable
kernels with explicit grid/block launch shapes.

Design (tpu-first): the idiomatic runtime kernel language on TPU is
**Pallas** (Python-authored, Mosaic-compiled), so ``PallasModule`` wraps a
user kernel function instead of a source string; grid/block launch
geometry maps to the Pallas ``grid`` + per-ref ``BlockSpec`` index maps.
Kernels run in interpret mode off-TPU so the same module works in tests.

    mod = mx.rtc.PallasModule(my_kernel, n_outputs=1)
    f = mod.get_kernel(out_shapes=[((1024,), 'float32')],
                       grid=(8,), in_specs=..., out_specs=...)
    y = f(x)        # NDArray in, NDArray out, autograd-transparent

``CudaModule(source)`` raises with guidance — CUDA C has no TPU target.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray.ops import _as_nd
from .ndarray.register import invoke

__all__ = ["PallasModule", "CudaModule"]


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


class PallasModule:
    """A user-authored Pallas kernel, callable over NDArrays.

    Parameters
    ----------
    kernel : callable(*in_refs, *out_refs)
        Pallas kernel body (refs follow jax.experimental.pallas).
    name : display name for profiler/debugging.
    """

    def __init__(self, kernel: Callable, name: Optional[str] = None) -> None:
        self._kernel = kernel
        self._name = name or getattr(kernel, "__name__", "pallas_kernel")

    def get_kernel(self, out_shapes: Sequence[Tuple[Tuple[int, ...], Any]],
                   grid: Optional[Tuple[int, ...]] = None,
                   in_specs: Any = None, out_specs: Any = None,
                   interpret: Optional[bool] = None,
                   vjp: Optional[Callable] = None,
                   **pallas_kwargs: Any) -> Callable:
        """Bind launch geometry; returns ``f(*ndarrays) -> NDArray(s)``.

        out_shapes: [(shape, dtype), ...] — one per kernel output ref.
        grid / in_specs / out_specs: forwarded to ``pallas_call``.
        interpret: force interpret mode (defaults to auto: off-TPU only).
        vjp: optional ``vjp(out_cot, *input_arrays) -> per-input cots``
            making the kernel autograd-capable (single-output kernels);
            without it the kernel is non-differentiable, like the
            reference's CudaModule kernels.
        """
        from jax.experimental import pallas as pl

        if interpret is None:
            interpret = not _on_tpu()
        shape_struct = [jax.ShapeDtypeStruct(s, jnp.dtype(d))
                        for s, d in out_shapes]
        single = len(shape_struct) == 1
        call_kwargs = dict(pallas_kwargs)
        if grid is not None:
            call_kwargs["grid"] = grid
        if in_specs is not None:
            call_kwargs["in_specs"] = in_specs
        if out_specs is not None:
            call_kwargs["out_specs"] = (
                out_specs[0] if single and isinstance(out_specs, (list,
                                                                  tuple))
                else out_specs)

        fn = pl.pallas_call(
            self._kernel,
            out_shape=shape_struct[0] if single else shape_struct,
            interpret=interpret, **call_kwargs)

        name = self._name

        def launch(*inputs):
            nds = [_as_nd(x) for x in inputs]
            if vjp is None:
                return invoke(f"rtc_{name}", lambda *arr: fn(*arr),
                              tuple(nds))
            from .ndarray.register import invoke_with_custom_vjp
            arrays = [n._data for n in nds]
            return invoke_with_custom_vjp(
                f"rtc_{name}", lambda *arr: fn(*arr), tuple(nds),
                lambda cot: vjp(cot, *arrays))

        launch.__name__ = name
        return launch


class CudaModule:
    """Unavailable on TPU; kept for API parity with guidance."""

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        raise MXNetError(
            "CudaModule (NVRTC CUDA C) has no TPU target. Author runtime "
            "kernels with mx.rtc.PallasModule — Pallas is the TPU-native "
            "kernel language (see /opt/skills/guides/pallas_guide.md and "
            "mxnet_tpu/ops/pallas/ for examples).")
