"""MX-D001 — determinism hygiene on seeded fault paths.

The chaos layer's contract (faults.py docstring) is that a plan + seed
replays the identical fault schedule in every process.  That contract
dies the moment plan evaluation — or control flow in a function hosting
a fault-injection site — depends on the wall clock or the global RNG:
the serving.worker / ps.server busy-pass-gate hardening in PRs 7-8 both
started as exactly this bug (a wall-clock deadline deciding whether the
loop made the pass on which a seeded fault would have fired).

Scope, tuned for signal:

* In ``faults.py`` (plan evaluation) every clock read and every
  global-RNG draw is flagged — evaluation must be a pure function of
  (plan, seed, hit count).
* Elsewhere, only functions that contain a ``maybe_fault(...)`` /
  ``maybe_corrupt(...)`` call are examined, and only *gating* reads are
  flagged: a clock read or RNG draw that occurs inside a branch/loop
  test or comparison, or whose assigned name feeds one later in the
  same function.  Pure measurement (``t0 = perf_counter()`` ...
  ``observe(perf_counter() - t0)``) around a fault site is fine — it
  cannot change how many times the site is hit.

``time.sleep`` is exempt (a delay injects latency, it does not *read*
the clock), and ``random.Random(seed)`` is exempt (constructing a
seeded stream is the fix, not the bug).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from .core import AnalysisContext, Finding, Source, dotted as _dotted

_CLOCK_READS = {
    "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "time.perf_counter_ns",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
_GLOBAL_RNG_EXEMPT = {"Random", "SystemRandom", "seed", "getstate",
                      "setstate"}
_FAULT_SITE_CALLS = {"maybe_fault", "maybe_corrupt"}


def _nondet_desc(call: ast.Call) -> Optional[str]:
    d = _dotted(call.func)
    if not d:
        return None
    if d in _CLOCK_READS:
        return f"wall-clock read {d}()"
    head, _, leaf = d.rpartition(".")
    if head == "random" and leaf not in _GLOBAL_RNG_EXEMPT:
        return f"global-RNG draw random.{leaf}()"
    return None


def _test_exprs(func: ast.AST) -> List[ast.AST]:
    """Every expression that gates control flow in ``func``."""
    tests: List[ast.AST] = []
    for sub in ast.walk(func):
        if isinstance(sub, (ast.If, ast.While, ast.IfExp)):
            tests.append(sub.test)
        elif isinstance(sub, ast.Assert):
            tests.append(sub.test)
        elif isinstance(sub, ast.Compare):
            tests.append(sub)
        elif isinstance(sub, ast.comprehension):
            tests.extend(sub.ifs)
    return tests


class _FuncChecker:
    def __init__(self, src: Source, findings: List[Finding]) -> None:
        self.src = src
        self.findings = findings
        self.strict = src.rel.endswith("faults.py")

    def check(self, func: ast.AST) -> None:
        # walk the function's own code only — nested defs/lambdas run
        # later, outside this function's fault-path dynamic extent
        own: List[ast.AST] = []
        stack: List[ast.AST] = [func]
        while stack:
            n = stack.pop()
            own.append(n)
            for child in ast.iter_child_nodes(n):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue
                stack.append(child)
        calls = [n for n in own if isinstance(n, ast.Call)]
        has_site = any(
            (_dotted(c.func) or "").rsplit(".", 1)[-1]
            in _FAULT_SITE_CALLS for c in calls)
        if not (self.strict or has_site):
            return
        nondet: List[Tuple[ast.Call, str]] = []
        for c in calls:
            desc = _nondet_desc(c)
            if desc:
                nondet.append((c, desc))
        if not nondet:
            return
        if self.strict:
            gating = set(id(c) for c, _ in nondet)
            tainted_names: Set[str] = set()
        else:
            tests = _test_exprs(func)
            in_tests = {id(n) for t in tests for n in ast.walk(t)}
            # names assigned from a nondet call, then used in a test
            tainted_names = set()
            for n in own:
                if isinstance(n, ast.Assign) and isinstance(
                        n.value, ast.Call) and _nondet_desc(n.value):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            tainted_names.add(t.id)
                elif (isinstance(n, ast.Assign)
                      and isinstance(n.value, ast.BinOp)):
                    # deadline = time.monotonic() + budget
                    for sub in ast.walk(n.value):
                        if isinstance(sub, ast.Call) and _nondet_desc(
                                sub):
                            for t in n.targets:
                                if isinstance(t, ast.Name):
                                    tainted_names.add(t.id)
            test_names = {n.id for t in tests for n in ast.walk(t)
                          if isinstance(n, ast.Name)}
            gating = {id(c) for c, _ in nondet if id(c) in in_tests}
            if tainted_names & test_names:
                gating |= {id(c) for c, _ in nondet}
        fn_name = getattr(func, "name", "<lambda>")
        for c, desc in nondet:
            if id(c) not in gating:
                continue
            where = ("plan evaluation (faults.py)" if self.strict
                     else f"{fn_name}(), which hosts a seeded fault "
                          "site")
            self.findings.append(Finding(
                "MX-D001", self.src.rel, c.lineno,
                f"{desc} gates control flow in {where}",
                "a plan + seed must replay the identical fault "
                "schedule: derive randomness from the clause's seeded "
                "random.Random, and keep wall-clock deadlines out of "
                "the path that decides whether the site is hit (count "
                "passes/steps instead)"))


class _Visitor(ast.NodeVisitor):
    def __init__(self, src: Source, findings: List[Finding]) -> None:
        self.checker = _FuncChecker(src, findings)

    def _visit_func(self, node) -> None:
        self.checker.check(node)
        for child in ast.iter_child_nodes(node):
            self.visit(child)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def analyze(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for src in ctx.sources:
        _Visitor(src, findings).visit(src.tree)
    return findings
