"""``python -m mxnet_tpu.analysis`` — the mxlint CI driver.

Default run (no arguments): analyze ``mxnet_tpu/`` + ``tools/`` with
every rule, apply ``ci/mxlint_waivers.toml``, fail (exit 1) on any
unwaived finding or any unused waiver.  This is the tier-1 gate
(``ci/run.sh mxlint``); the old ``envdoc``/``faultdoc`` variants are
thin aliases onto ``--rules`` subsets.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .core import (RULES, WaiverError, load_waivers, repo_root,
                   run_analysis)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.analysis",
        description="mxlint: the repo's AST concurrency & invariant "
                    "analyzer (rule catalog: docs/static_analysis.md)")
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files/dirs to analyze (default: mxnet_tpu/ "
                         "and tools/ under the repo root)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--waivers", type=Path, default=None,
                    help="waiver file (default: ci/mxlint_waivers.toml; "
                         "missing file = no waivers)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid, desc in sorted(RULES.items()):
            print(f"{rid}  {desc}")
        return 0

    root = repo_root()
    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    waiver_path = args.waivers or (root / "ci" / "mxlint_waivers.toml")
    try:
        waivers = load_waivers(waiver_path)
        report = run_analysis(paths=args.paths or None, root=root,
                              rules=rules, waivers=waivers)
    except (WaiverError, ValueError) as e:
        print(f"mxlint: {e}", file=sys.stderr)
        return 1

    if args.json:
        print(json.dumps({
            "findings": [f.as_dict() for f in report.findings],
            "waived": [{"finding": f.as_dict(),
                        "justification": w.justification}
                       for f, w in report.waived],
            "unused_waivers": [
                {"rule": w.rule, "path": w.path,
                 "line": w.source_line} for w in report.unused_waivers],
        }, indent=2))
    else:
        for f in report.findings:
            print(f.format())
        for w in report.unused_waivers:
            print(f"{waiver_path}:{w.source_line}: unused waiver "
                  f"({w.rule} on {w.path}) — the finding it suppressed "
                  "is gone; delete the waiver so the baseline shrinks")
        n, w_n, u = (len(report.findings), len(report.waived),
                     len(report.unused_waivers))
        verdict = "PASS" if report.ok else "FAIL"
        print(f"mxlint: {verdict} — {n} finding(s), {w_n} waived, "
              f"{u} unused waiver(s)")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
