"""mxlint — the repo's own static concurrency & invariant analyzer.

PRs 6-10 turned this reproduction into a genuinely concurrent system
(supervisor/worker threads in serving, heartbeat daemons and socket
loops in kvstore_async, a prefetch producer, cross-process compile-cache
writers) and the recurring review findings all fell into a handful of
mechanical classes: blocking calls made while holding a lock, wall-clock
or global-RNG reads on seeded-deterministic fault paths, reads of a
buffer after it was donated, and config/metric/fault surfaces added
without their registration or docs.  This package holds those invariants
by tooling instead of vigilance — the same correctness-tooling instinct
as the reference's cpplint/pylint/sanitizer CI tiers, specialized to
this repo (paper §runtime: the dependency engine's safety rests on
exactly the lock/async discipline we reimplement in Python threads).

Two halves:

* the **static analyzer** (``python -m mxnet_tpu.analysis``): parses the
  whole ``mxnet_tpu/`` + ``tools/`` tree with ``ast`` and reports typed
  findings (rule id, file:line, message, fix hint), gated in tier-1 CI
  with a checked-in waiver file (``ci/mxlint_waivers.toml``).  Rule
  catalog: docs/static_analysis.md.

* the **runtime lock-order sanitizer** (:mod:`.lockdep`, enabled via
  ``MXNET_SANITIZE=locks``): patches ``threading.Lock``/``RLock``
  creation to record per-thread acquisition stacks and asserts a
  globally consistent acquisition order, reporting inversions with both
  stacks.  It runs under the chaos/resilience smokes, where the thread
  interleavings actually happen.

Imports stay lazy: production processes that only enable the sanitizer
must not pay for the ast machinery, and the sanitizer must be
installable before the rest of the package creates its locks.
"""
from typing import Any

__all__ = [
    "Finding", "Waiver", "run_analysis", "load_waivers", "lockdep",
]

_LAZY = {
    "Finding": ("mxnet_tpu.analysis.core", "Finding"),
    "Waiver": ("mxnet_tpu.analysis.core", "Waiver"),
    "run_analysis": ("mxnet_tpu.analysis.core", "run_analysis"),
    "load_waivers": ("mxnet_tpu.analysis.core", "load_waivers"),
    "lockdep": ("mxnet_tpu.analysis.lockdep", None),
}


def __getattr__(name: str) -> Any:
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib
    mod = importlib.import_module(mod_name)
    return mod if attr is None else getattr(mod, attr)
