"""MX-N001 — donation safety: no reads of a buffer binding after the
call that donated it.

The repo's donation idiom (PR 9's targeted barriers) is a two-beat
sequence::

    _bulk.flush_holding(donated, "mutation")   # barrier: materialize
    out = self._step_fn(param_arrays, ...)     # donate_argnums call

``flush_holding(arrays)`` names exactly the buffers the *next* compiled
call donates; after that call runs, XLA has deleted their backing
memory and any further host read of the same bindings is a
use-after-free that jax reports (when it does) as a cryptic "donated
buffer was deleted" far from the cause.  The rule therefore keys on the
``flush_holding`` marker: the donated set is the flush argument
(expanded one level through ``a + b`` concatenation, ``[x, y]``
literals, and ``list(x)`` copies, following a local ``donated = ...``
assignment); the donating call is the first later statement that passes
any of those bindings into a non-builtin call (a ``len(params)``
between barrier and step reads still-live buffers and is fine); every
read *after that statement* is flagged, unless the binding was
reassigned in between.

Computed arguments the expansion cannot name (comprehensions, attribute
chains) are skipped — re-reading the same binding is the pattern that
bites, and alias chasing would drown the signal.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from .core import AnalysisContext, Finding, Source

#: call leaf names that mark the donated set (their first positional
#: argument names the buffers the next compiled call donates)
DONATION_MARKERS = {"flush_holding"}

#: builtins whose calls cannot be the donating compiled call — a
#: len(params) between the barrier and the step call is a legal read
#: of still-live buffers, not the donation point
_BENIGN_CALLS = {
    "len", "id", "isinstance", "repr", "str", "print", "type", "bool",
    "sum", "min", "max", "sorted", "enumerate", "zip", "list", "tuple",
    "set", "dict", "iter", "next", "format", "hash", "any", "all",
}


def _call_leaf(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _names_in_value(expr: ast.AST) -> Set[str]:
    """Names of array bindings in a donated-set expression: handles
    Name, a + b chains, [x, y] literals, and list(x) copies."""
    if isinstance(expr, ast.Name):
        return {expr.id}
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _names_in_value(expr.left) | _names_in_value(expr.right)
    if isinstance(expr, (ast.List, ast.Tuple)):
        out: Set[str] = set()
        for elt in expr.elts:
            if isinstance(elt, ast.Name):
                out.add(elt.id)
        return out
    if (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Name)
            and expr.func.id == "list" and len(expr.args) == 1):
        return _names_in_value(expr.args[0])
    return set()


def _assigned_names(stmt: ast.stmt) -> Set[str]:
    out: Set[str] = set()
    targets: Iterable[ast.AST] = ()
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = (stmt.target,)
    elif isinstance(stmt, ast.For):
        targets = (stmt.target,)
    elif isinstance(stmt, ast.With):
        targets = tuple(i.optional_vars for i in stmt.items
                        if i.optional_vars is not None)
    for t in targets:
        for sub in ast.walk(t):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
    return out


def _is_donating_stmt(stmt: ast.stmt, names: Set[str]) -> bool:
    """Does this statement pass a donated binding as an argument to a
    call that could be the donate_argnums-compiled call?"""
    for sub in ast.walk(stmt):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        if not isinstance(sub, ast.Call):
            continue
        leaf = _call_leaf(sub.func)
        if leaf in _BENIGN_CALLS or leaf in DONATION_MARKERS:
            continue
        args = list(sub.args) + [k.value for k in sub.keywords]
        for a in args:
            for n in ast.walk(a):
                if (isinstance(n, ast.Name)
                        and isinstance(n.ctx, ast.Load)
                        and n.id in names):
                    return True
    return False


def _reads_in(stmt: ast.stmt, names: Set[str]) -> List[ast.Name]:
    reads = []
    for sub in ast.walk(stmt):
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue  # deferred execution — out of scope for the rule
        if (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)
                and sub.id in names):
            reads.append(sub)
    return reads


class _BodyWalker(ast.NodeVisitor):
    def __init__(self, src: Source, findings: List[Finding]) -> None:
        self.src = src
        self.findings = findings

    def _scan_body(self, body: List[ast.stmt]) -> None:
        # local one-level expansion: donated = param_arrays + list(arrays)
        local_defs: Dict[str, Set[str]] = {}
        for stmt in body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                local_defs[stmt.targets[0].id] = _names_in_value(
                    stmt.value)
        for i, stmt in enumerate(body):
            donated: Set[str] = set()
            don_line = stmt.lineno
            for sub in ast.walk(stmt):
                if (isinstance(sub, ast.Call)
                        and _call_leaf(sub.func) in DONATION_MARKERS
                        and sub.args):
                    direct = _names_in_value(sub.args[0])
                    donated |= direct
                    for n in list(direct):
                        donated |= local_defs.get(n, set())
                    don_line = sub.lineno
            if not donated:
                continue
            live = set(donated)
            donating_stmt_seen = False
            donate_line = don_line
            for later in body[i + 1:]:
                if not live:
                    break
                if not donating_stmt_seen:
                    # buffers stay live until the donate_argnums call
                    # actually runs: benign reads (len(params), ...)
                    # before it are fine — the anchor is the first
                    # non-builtin call fed a donated binding
                    if _is_donating_stmt(later, live):
                        donating_stmt_seen = True
                        donate_line = later.lineno
                else:
                    reads = _reads_in(later, live)
                    for read in reads:
                        self.findings.append(Finding(
                            "MX-N001", self.src.rel, read.lineno,
                            f"read of {read.id!r} after its buffers "
                            f"were donated by the call at line "
                            f"{donate_line} (donation barrier "
                            f"flush_holding at line {don_line}): the "
                            "backing memory may already be deleted",
                            "donate last — reorder so every read "
                            "happens before the donating call, or "
                            "rebind the name to the fresh outputs "
                            "first"))
                live -= _assigned_names(later)

    def generic_visit(self, node: ast.AST) -> None:
        for field_body in ("body", "orelse", "finalbody"):
            body = getattr(node, field_body, None)
            if isinstance(body, list) and body and isinstance(
                    body[0], ast.stmt):
                self._scan_body(body)
        super().generic_visit(node)


def analyze(ctx: AnalysisContext) -> List[Finding]:
    findings: List[Finding] = []
    for src in ctx.sources:
        _BodyWalker(src, findings).visit(src.tree)
    return findings
