"""mxlint core: source collection, findings, waivers, and the rule runner.

Everything here is stdlib-only (``ast`` + ``dataclasses``): the analyzer
must run in a bare CI interpreter in well under the 30s tier-1 budget.
Rule implementations live in sibling modules (locks, determinism,
donation, registration); this module owns the shared machinery:

* :class:`Source` — one parsed file (path, AST, text).
* :class:`Finding` — one typed report: rule id, file:line, message, hint.
* :class:`Waiver` + :func:`load_waivers` — the checked-in suppression
  list (``ci/mxlint_waivers.toml``).  A waiver must carry a
  justification, and a waiver that matches nothing is itself an error,
  so the baseline only ever shrinks.
* :func:`run_analysis` — collect sources, run rules, apply waivers.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

#: rule id -> one-line description (the catalog; docs/static_analysis.md
#: is the long-form version and tests assert the two stay in sync).
RULES: Dict[str, str] = {
    "MX-E000": "source file failed to parse (syntax error)",
    "MX-L001": "blocking call while holding a lock",
    "MX-L002": "inconsistent lock acquisition order (potential deadlock)",
    "MX-D001": "wall-clock or global-RNG read on a seeded fault path",
    "MX-N001": "read of a buffer binding after it was donated",
    "MX-R001": "MXNET_* env var read without register_env registration",
    "MX-R002": "metric family not documented in docs/observability.md",
    "MX-R003": "fault site not documented in docs/fault_tolerance.md",
    "MX-R004": "docs/env_vars.md is stale vs the registered env surface",
}

#: rule-group -> rule ids it can emit (drives --rules group skipping).
RULE_GROUPS: Dict[str, Tuple[str, ...]] = {
    "locks": ("MX-L001", "MX-L002"),
    "determinism": ("MX-D001",),
    "donation": ("MX-N001",),
    "registration": ("MX-R001", "MX-R002", "MX-R003", "MX-R004"),
}


def dotted(node: ast.AST) -> Optional[str]:
    """Dotted source name of an attribute chain rooted at a Name —
    'self._lock', 'threading.Lock', 'os.environ.get' — or None.
    Shared by every rule module so they stay consistent in what
    expressions they can name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-root-relative, forward slashes
    line: int
    message: str
    hint: str = ""

    def format(self) -> str:
        s = f"{self.path}:{self.line}: {self.rule} {self.message}"
        if self.hint:
            s += f"\n    hint: {self.hint}"
        return s

    def as_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint}


@dataclass
class Waiver:
    rule: str
    path: str
    justification: str
    contains: str = ""     # substring of the finding message ("" = any)
    source_line: int = 0   # where in the waiver file (for errors)
    used: int = 0

    def matches(self, f: Finding) -> bool:
        return (f.rule == self.rule and f.path == self.path
                and (not self.contains or self.contains in f.message))


class WaiverError(ValueError):
    """Malformed waiver file — fails the run regardless of findings."""


def _parse_toml_subset(text: str, origin: str) -> List[Dict[str, object]]:
    """Parse the waiver file's TOML subset: ``[[waiver]]`` tables of
    ``key = "string" | int | bool`` pairs plus comments.  This rig's
    interpreter predates :mod:`tomllib`; the subset keeps the checked-in
    format standard TOML so the file survives an interpreter upgrade."""
    tables: List[Dict[str, object]] = []
    current: Optional[Dict[str, object]] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[waiver]]":
            current = {"__line__": lineno}
            tables.append(current)
            continue
        if line.startswith("["):
            raise WaiverError(
                f"{origin}:{lineno}: only [[waiver]] tables are "
                f"recognized, got {line!r}")
        if "=" not in line:
            raise WaiverError(f"{origin}:{lineno}: expected key = value, "
                              f"got {line!r}")
        if current is None:
            raise WaiverError(f"{origin}:{lineno}: key outside a "
                              "[[waiver]] table")
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        if val.startswith('"'):
            # scan for the UNESCAPED closing quote — rfind would let a
            # trailing comment containing a quote corrupt the value
            end = -1
            i = 1
            while i < len(val):
                if val[i] == "\\":
                    i += 2
                    continue
                if val[i] == '"':
                    end = i
                    break
                i += 1
            if end < 0:
                raise WaiverError(
                    f"{origin}:{lineno}: unterminated string")
            rest = val[end + 1:].strip()
            if rest and not rest.startswith("#"):
                raise WaiverError(
                    f"{origin}:{lineno}: unexpected text after "
                    f"closing quote: {rest!r}")
            parsed: object = (val[1:end]
                              .replace('\\"', '"').replace("\\\\", "\\"))
        elif val in ("true", "false"):
            parsed = val == "true"
        else:
            val = val.split("#", 1)[0].strip()
            try:
                parsed = int(val)
            except ValueError:
                raise WaiverError(
                    f"{origin}:{lineno}: unsupported value {val!r} "
                    "(strings must be double-quoted)") from None
        current[key] = parsed
    return tables


def load_waivers(path: Path) -> List[Waiver]:
    """Load ``ci/mxlint_waivers.toml``.  Missing file means no waivers;
    a present-but-malformed file is an error (a silently ignored waiver
    file would un-gate the lint)."""
    if not path.exists():
        return []
    tables = _parse_toml_subset(path.read_text(), str(path))
    waivers: List[Waiver] = []
    for t in tables:
        line = int(t.pop("__line__", 0))
        missing = [k for k in ("rule", "path", "justification")
                   if not t.get(k)]
        if missing:
            raise WaiverError(
                f"{path}:{line}: waiver missing required field(s) "
                f"{missing} — every waiver needs rule, path, and a "
                "justification")
        rule = str(t["rule"])
        if rule not in RULES:
            raise WaiverError(
                f"{path}:{line}: unknown rule id {rule!r} "
                f"(known: {sorted(RULES)})")
        unknown = set(t) - {"rule", "path", "justification", "contains"}
        if unknown:
            raise WaiverError(
                f"{path}:{line}: unknown waiver field(s) "
                f"{sorted(unknown)}")
        waivers.append(Waiver(
            rule=rule, path=str(t["path"]),
            justification=str(t["justification"]),
            contains=str(t.get("contains", "")), source_line=line))
    return waivers


@dataclass
class Source:
    path: Path
    rel: str                 # root-relative posix path
    text: str
    tree: ast.Module
    modname: str             # dotted, e.g. mxnet_tpu.kvstore_async


def repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


_SKIP_DIRS = {"__pycache__", ".git", "fixtures"}


def collect_sources(paths: Sequence[Path], root: Path
                    ) -> Tuple[List[Source], List[Finding]]:
    """Parse every ``*.py`` under ``paths``.  Unparseable files become
    MX-E000 findings rather than crashing the run — a syntax error in
    one module must not hide findings in the rest."""
    sources: List[Source] = []
    errors: List[Finding] = []
    files: List[Path] = []
    for p in paths:
        if p.is_file():
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(Path(dirpath) / fn)
    for f in files:
        try:
            rel = f.resolve().relative_to(root).as_posix()
        except ValueError:
            rel = f.as_posix()
        text = f.read_text()
        try:
            tree = ast.parse(text, filename=str(f))
        except SyntaxError as e:
            errors.append(Finding(
                "MX-E000", rel, e.lineno or 1,
                f"syntax error: {e.msg}",
                "fix the syntax error; the analyzer skipped this file"))
            continue
        modname = rel[:-3].replace("/", ".")
        if modname.endswith(".__init__"):
            modname = modname[: -len(".__init__")]
        sources.append(Source(f, rel, text, tree, modname))
    return sources, errors


@dataclass
class AnalysisContext:
    """Everything a rule module needs: the parsed tree plus doc texts."""
    root: Path
    sources: List[Source]
    docs_root: Path
    check_env_doc: bool = True   # MX-R004 imports the full package; off
    #                              for fixture-dir runs in tests
    #: sources whose register_env calls define the registered set for
    #: MX-R001 — the whole default tree even on explicit-path runs
    registration_sources: Optional[List[Source]] = None
    _docs: Dict[str, str] = field(default_factory=dict)

    def doc(self, name: str) -> str:
        if name not in self._docs:
            p = self.docs_root / name
            self._docs[name] = p.read_text() if p.exists() else ""
        return self._docs[name]


@dataclass
class Report:
    findings: List[Finding]           # unwaived — these fail the run
    waived: List[Tuple[Finding, Waiver]]
    unused_waivers: List[Waiver]

    @property
    def ok(self) -> bool:
        return not self.findings and not self.unused_waivers


def _severity_key(f: Finding) -> Tuple:
    return (f.rule, f.path, f.line)


def run_analysis(paths: Optional[Sequence[Path]] = None,
                 root: Optional[Path] = None,
                 rules: Optional[Sequence[str]] = None,
                 waivers: Optional[Sequence[Waiver]] = None,
                 docs_root: Optional[Path] = None,
                 check_env_doc: Optional[bool] = None) -> Report:
    """Run the analyzer.

    ``paths`` defaults to ``mxnet_tpu/`` + ``tools/`` under the repo
    root.  ``rules`` filters to a subset of rule ids (a rule group whose
    ids are all filtered out is skipped entirely).  When a rule filter
    is active, unused-waiver enforcement only applies to waivers for the
    selected rules — a partial run must not flag the other waivers as
    stale.
    """
    root = (root or repo_root()).resolve()
    default_paths = paths is None
    if paths is None:
        paths = [root / "mxnet_tpu", root / "tools"]

    selected = set(rules) if rules else set(RULES)
    unknown = selected - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule id(s): {sorted(unknown)} "
                         f"(known: {sorted(RULES)})")
    if check_env_doc is None:
        # fixture-dir runs (tests) must not import the whole package
        check_env_doc = default_paths
    # the render imports the full package (jax included): skip it when
    # MX-R004 findings would be filtered out anyway
    check_env_doc = check_env_doc and "MX-R004" in selected

    sources, findings = collect_sources([Path(p) for p in paths], root)
    reg_sources = sources
    if not default_paths:
        # MX-R001 must judge reads against the WHOLE tree's
        # register_env surface, or a single-file run reports vars
        # registered elsewhere as unregistered
        default_dirs = [p for p in (root / "mxnet_tpu", root / "tools")
                        if p.is_dir()]
        if default_dirs:
            reg_sources, _ = collect_sources(default_dirs, root)
            reg_sources = reg_sources + sources
    ctx = AnalysisContext(root=root, sources=sources,
                          docs_root=docs_root or (root / "docs"),
                          check_env_doc=check_env_doc,
                          registration_sources=reg_sources)

    from . import locks, determinism, donation, registration
    groups = {"locks": locks.analyze, "determinism": determinism.analyze,
              "donation": donation.analyze,
              "registration": registration.analyze}
    for gname, fn in groups.items():
        if selected.intersection(RULE_GROUPS[gname]):
            findings.extend(fn(ctx))
    # MX-E000 bypasses the rule filter: a subset run that silently
    # skipped an unparseable file would report PASS having checked
    # nothing in it
    findings = sorted(
        (f for f in findings
         if f.rule in selected or f.rule == "MX-E000"),
        key=_severity_key)

    wlist = list(waivers or [])
    unwaived: List[Finding] = []
    waived: List[Tuple[Finding, Waiver]] = []
    for f in findings:
        w = next((w for w in wlist if w.matches(f)), None)
        if w is None:
            unwaived.append(f)
        else:
            w.used += 1
            waived.append((f, w))
    # Unused-waiver enforcement is scoped to what this run could have
    # matched: a --rules or explicit-path subset run must not flag the
    # other waivers as stale (only the full default run shrinks the
    # baseline).
    analyzed = {s.rel for s in sources}
    unused = [w for w in wlist
              if not w.used and (rules is None or w.rule in selected)
              and (default_paths or w.path in analyzed)]
    return Report(unwaived, waived, unused)
