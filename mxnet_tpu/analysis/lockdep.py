"""Runtime lock-order sanitizer (``MXNET_SANITIZE=locks``).

The static MX-L002 rule sees only syntactic nesting; real inversions
happen across call chains and threads the AST cannot follow.  This is
the dynamic half, modeled on Linux lockdep: patch ``threading.Lock`` /
``threading.RLock`` *creation* so every lock allocated from this repo's
code is wrapped, record per-thread acquisition stacks, and maintain a
global acquired-while-holding graph keyed by the lock's allocation site
(its "lock class", so the per-key lock instances in kvstore collapse
into one node).  The first time an edge B -> A appears whose reverse
A -> B was already observed, the sanitizer reports the inversion with
both acquisition stacks — the exact two code paths that can deadlock —
and (by default) raises :class:`LockOrderViolation`.

Enablement: ``MXNET_SANITIZE=locks`` in the environment before
``import mxnet_tpu`` (the package installs the patch first thing, so
every lock the runtime creates afterwards is tracked), or
:func:`install` programmatically in tests.  CI runs the chaos and
resilience smokes under it — the legs whose thread interleavings
actually exercise the lock graph.

Scope and cost: only locks *allocated from files under this repo* are
wrapped — jax/XLA internals keep raw ``_thread`` locks (zero overhead,
no foreign-code false positives).  Tracked acquisition captures a
~10-frame summary per acquire; that is microseconds, fine for smokes,
not meant for production serving (which is why it is an opt-in
sanitizer, not a default).

``cv.wait()`` on a tracked lock releases and reacquires through the
lock's own ``acquire``/``release`` (plain Lock) or the C-level
``_release_save`` protocol (RLock); the RLock fast path bypasses the
tracker for the duration of the wait, which is sound — the waiting
thread acquires nothing while blocked.
"""
from __future__ import annotations

import os
import sys
import threading
import _thread
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["install", "uninstall", "installed", "reset",
           "violations", "LockOrderViolation"]

_REPO_ROOT = str(Path(__file__).resolve().parents[2])
_SELF_FILE = str(Path(__file__).resolve())


def _internal_frame(fn: str) -> bool:
    return fn == _SELF_FILE or fn.endswith("threading.py")

_real_lock = _thread.allocate_lock
_real_rlock = getattr(_thread, "RLock")

# raw (untracked) lock guarding the sanitizer's own state
_STATE_LOCK = _real_lock()
_installed = False
_action = "raise"

# (site_a, site_b) -> (stack_held, stack_acquired, thread_name): the
# first observation of "site_b acquired while site_a held"
_EDGES: Dict[Tuple[str, str], Tuple[str, str, str]] = {}
_VIOLATIONS: List[str] = []
_TLS = threading.local()
# id(inner lock) -> the ACQUIRING thread's held list: a plain Lock may
# legally be released from another thread (handoff patterns) and the
# stale entry must come off the owner's list, not the releaser's
_OWNER_HELD: Dict[int, List] = {}


class LockOrderViolation(AssertionError):
    """Two code paths acquire the same two lock classes in opposite
    orders — a latent deadlock.  The message carries both stacks."""


def _held() -> List[Tuple[str, Any, str]]:
    """Per-thread held list: (site, lock instance, acquisition stack)."""
    h = getattr(_TLS, "held", None)
    if h is None:
        h = _TLS.held = []
    return h


def _interesting(filename: str) -> bool:
    return filename.startswith(_REPO_ROOT) and filename != _SELF_FILE


def _alloc_site() -> Optional[str]:
    """Allocation site of the lock being created: the nearest caller
    frame outside threading.py and this module.  None = don't track."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename
        if not _internal_frame(fn):
            if _interesting(fn):
                rel = fn[len(_REPO_ROOT):].lstrip("/")
                return f"{rel}:{f.f_lineno}"
            return None
        f = f.f_back
    return None


def _light_stack(limit: int = 10) -> str:
    frames: List[str] = []
    f: Any = sys._getframe(2)
    while f is not None and len(frames) < limit:
        fn = f.f_code.co_filename
        if not _internal_frame(fn):
            short = (fn[len(_REPO_ROOT):].lstrip("/")
                     if fn.startswith(_REPO_ROOT) else fn)
            frames.append(f"{short}:{f.f_lineno} in {f.f_code.co_name}")
        f = f.f_back
    return "\n        ".join(frames) or "<no frames>"


def _record_acquire(site: str, inst: Any, reentrant: bool) -> None:
    held = _held()
    if reentrant and any(h[1] is inst for h in held):
        held.append((site, inst, ""))   # reentrant: no new edges
        return
    stack = _light_stack()
    tname = threading.current_thread().name
    problems: List[str] = []
    with _STATE_LOCK:
        for held_site, held_inst, held_stack in held:
            if held_inst is inst or held_site == site:
                continue   # same instance / same class: unorderable
            edge = (held_site, site)
            if edge not in _EDGES:
                _EDGES[edge] = (held_stack, stack, tname)
                rev = (site, held_site)
                if rev in _EDGES:
                    r_held, r_acq, r_thread = _EDGES[rev]
                    msg = (
                        "lock-order inversion (potential deadlock):\n"
                        f"  lock classes (by allocation site): "
                        f"A={held_site}  B={site}\n"
                        f"  this thread ({tname}) acquires B while "
                        "holding A:\n"
                        f"    A held since:\n        {held_stack}\n"
                        f"    B acquired at:\n        {stack}\n"
                        f"  but thread {r_thread!r} earlier acquired A "
                        "while holding B:\n"
                        f"    B held since:\n        {r_held}\n"
                        f"    A acquired at:\n        {r_acq}\n"
                        "  fix: pick one global order for these locks "
                        "and restructure one site; see "
                        "docs/static_analysis.md#lockdep")
                    _VIOLATIONS.append(msg)
                    problems.append(msg)
    held.append((site, inst, stack))
    with _STATE_LOCK:
        _OWNER_HELD[id(inst)] = held
    for msg in problems:
        if _action == "raise":
            # undo this acquisition before raising out of acquire()/
            # __enter__: the with-body will never run and __exit__ will
            # never fire, so leaving the lock held would convert the
            # report into a process-wide deadlock
            with _STATE_LOCK:
                _forget(held, inst)
            inst.release()
            raise LockOrderViolation(msg)
        print(f"mxnet_tpu.analysis.lockdep: {msg}", file=sys.stderr)


def _forget(lst: List, inst: Any) -> None:
    """Remove the newest entry for ``inst`` from ``lst``; must be
    called with ``_STATE_LOCK`` held."""
    for i in range(len(lst) - 1, -1, -1):
        if lst[i][1] is inst:
            del lst[i]
            break
    if not any(h[1] is inst for h in lst):
        _OWNER_HELD.pop(id(inst), None)


def _record_release(inst: Any) -> None:
    held = _held()
    with _STATE_LOCK:
        if any(h[1] is inst for h in held):
            _forget(held, inst)
            return
        # released by a different thread than acquired it (Lock
        # handoff): clean the ACQUIRER's list or it would carry a
        # stale entry recording false edges forever
        owner = _OWNER_HELD.get(id(inst))
        if owner is not None:
            _forget(owner, inst)


class _TrackedLockBase:
    _reentrant = False

    def __init__(self, inner: Any, site: str) -> None:
        self._inner = inner
        self._site = site

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            _record_acquire(self._site, self._inner, self._reentrant)
        return got

    acquire_lock = acquire   # _thread.lock alias

    def release(self) -> None:
        self._inner.release()
        _record_release(self._inner)

    release_lock = release

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def _at_fork_reinit(self) -> None:
        self._inner._at_fork_reinit()
        _TLS.held = []

    def __getattr__(self, name: str) -> Any:
        # Condition's C-protocol hooks (_release_save/_acquire_restore/
        # _is_owned) and anything else forward to the real lock
        return getattr(self._inner, name)

    def __repr__(self) -> str:
        return (f"<lockdep-tracked {self._inner!r} "
                f"allocated at {self._site}>")


class _TrackedLock(_TrackedLockBase):
    _reentrant = False


class _TrackedRLock(_TrackedLockBase):
    _reentrant = True


def _make_lock() -> Any:
    inner = _real_lock()
    site = _alloc_site()
    return inner if site is None else _TrackedLock(inner, site)


def _make_rlock() -> Any:
    inner = _real_rlock()
    site = _alloc_site()
    return inner if site is None else _TrackedRLock(inner, site)


def install(action: Optional[str] = None) -> None:
    """Patch ``threading.Lock``/``RLock`` so repo-allocated locks are
    order-tracked.  ``action``: 'raise' (default) or 'warn'; the env
    override is ``MXNET_SANITIZE_LOCKS_ACTION``."""
    global _installed, _action
    _action = (action
               or os.environ.get("MXNET_SANITIZE_LOCKS_ACTION", "")
               or "raise")
    if _action not in ("raise", "warn"):
        raise ValueError("MXNET_SANITIZE_LOCKS_ACTION must be 'raise' "
                         f"or 'warn', got {_action!r}")
    if _installed:
        return
    threading.Lock = _make_lock            # type: ignore[misc]
    threading.RLock = _make_rlock          # type: ignore[misc]
    _installed = True


def uninstall() -> None:
    """Restore the real factories (tests).  Already-wrapped locks keep
    working — only new allocations stop being tracked."""
    global _installed
    threading.Lock = _real_lock            # type: ignore[misc]
    threading.RLock = _real_rlock          # type: ignore[misc]
    _installed = False


def installed() -> bool:
    return _installed


def reset() -> None:
    """Drop the observed edge graph and violation log (tests)."""
    with _STATE_LOCK:
        _EDGES.clear()
        _VIOLATIONS.clear()
        _OWNER_HELD.clear()


def violations() -> List[str]:
    with _STATE_LOCK:
        return list(_VIOLATIONS)
