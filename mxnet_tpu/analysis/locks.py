"""Lock-discipline rules.

MX-L001 — blocking call while a lock is held.  The recurring PR-6..10
review class: a ``with self._lock:`` body that does socket I/O, joins a
thread, sleeps, does a blocking queue op, waits on a foreign condition,
compiles, or forces a host read (``asnumpy``/``block_until_ready``/
``.item()``).  Every such call serializes unrelated threads behind the
lock — the PR-8 snapshot-leaf-flatten-under-``_global_lock`` bug, found
then only by review.  Detection is per-module with a bounded
call-graph closure: a call made while holding a lock to a local
function/method that (transitively, within the module) performs a
blocking op is flagged at the call site with the witness chain.

MX-L002 — inconsistent lock acquisition order.  Nested ``with`` blocks
(directly, or via a one-module call chain) define directed edges
lock_A -> lock_B; a cycle in the global graph across all modules means
two threads can deadlock.  Lock identity is the *definition site*
(``module.Class.attr``), the same "lock class" generalization Linux
lockdep uses, so per-key lock instances created at one site collapse
into one node.

Known limits (documented in docs/static_analysis.md): blocking ops
reached through cross-module calls are not propagated (the runtime
lockdep sanitizer covers the dynamic side), and a ``cond.wait()`` on the
condition guarding the innermost ``with`` is correctly treated as
*releasing* that lock — it only flags when some other lock stays held.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import AnalysisContext, Finding, Source, dotted as _dotted

_LOCK_FACTORIES = {"Lock", "RLock"}
_COND_FACTORIES = {"Condition"}

#: attribute names that end a thread-join heuristic discussion: str.join
#: always takes exactly one iterable positional; Thread.join takes none
#: or a numeric timeout.
_SOCKET_BLOCKING = {"recv", "recvfrom", "recv_into", "accept", "sendall"}
_SUBPROCESS_FUNCS = {"run", "call", "check_call", "check_output"}


@dataclass
class _FuncInfo:
    qual: str                      # module.Class.fn or module.fn
    rel: str
    node: ast.AST
    cls: Optional[str]
    blocking: List[Tuple[int, str]] = field(default_factory=list)
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    calls: List[Tuple[str, int]] = field(default_factory=list)  # local


@dataclass
class _ModuleLocks:
    defs: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    # condition name -> underlying lock name (Condition(self._lock))
    cond_underlying: Dict[str, str] = field(default_factory=dict)


class _DefCollector(ast.NodeVisitor):
    """Pass A: find every lock/condition definition site in a module."""

    def __init__(self, src: Source, mod: _ModuleLocks,
                 attr_index: Dict[str, Set[str]]) -> None:
        self.src = src
        self.mod = mod
        self.attr_index = attr_index
        self.cls: List[str] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls.append(node.name)
        self.generic_visit(node)
        self.cls.pop()

    def _target_name(self, target: ast.AST) -> Optional[str]:
        m = self.src.modname
        if isinstance(target, ast.Name) and not self.cls:
            return f"{m}.{target.id}"
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self" and self.cls):
            return f"{m}.{self.cls[-1]}.{target.attr}"
        if isinstance(target, ast.Subscript):
            inner = self._target_name(target.value)
            return f"{inner}[]" if inner else None
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        val = node.value
        calls = []
        if isinstance(val, ast.Call):
            calls = [val]
        elif isinstance(val, (ast.ListComp, ast.List)):
            # self._locks = [threading.Lock() for _ in ...]
            elt = (val.elt if isinstance(val, ast.ListComp)
                   else (val.elts[0] if val.elts else None))
            if isinstance(elt, ast.Call):
                calls = [elt]
        for call in calls:
            d = _dotted(call.func) or ""
            leaf = d.rsplit(".", 1)[-1]
            if leaf in _LOCK_FACTORIES | _COND_FACTORIES:
                for t in node.targets:
                    name = self._target_name(t)
                    if not name:
                        continue
                    if isinstance(val, (ast.ListComp, ast.List)):
                        name += "[]"
                    self.mod.defs[name] = (self.src.rel, node.lineno)
                    self.attr_index.setdefault(
                        name.rsplit(".", 1)[-1].rstrip("[]"),
                        set()).add(name)
                    if leaf in _COND_FACTORIES and call.args:
                        u = _dotted(call.args[0])
                        if u:
                            self.mod.cond_underlying[name] = u
        self.generic_visit(node)


def _blocking_desc(call: ast.Call) -> Optional[str]:
    """Why this call can block (or force a host/device sync), or None."""
    func = call.func
    if isinstance(func, ast.Name):
        if func.id == "sleep":
            return "time.sleep"
        return None
    if not isinstance(func, ast.Attribute):
        return None
    attr = func.attr
    recv = _dotted(func.value)
    if recv == "time" and attr == "sleep":
        return "time.sleep"
    if recv == "subprocess" and attr in _SUBPROCESS_FUNCS:
        return f"subprocess.{attr} (waits for the child)"
    if recv == "re" and attr == "compile":
        return None
    if attr in _SOCKET_BLOCKING:
        return f"socket .{attr}()"
    if attr == "connect" and recv and "sock" in recv.lower():
        return "socket .connect()"
    if attr == "communicate":
        return ".communicate() (waits for the child)"
    if attr == "join":
        # str.join always takes exactly one iterable positional;
        # Thread.join takes none, or a numeric timeout
        if not call.args and not any(k.arg == "timeout"
                                     for k in call.keywords):
            if call.keywords and all(k.arg != "timeout"
                                     for k in call.keywords):
                return None
            return "Thread.join()"
        if (len(call.args) == 1
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, (int, float))):
            return "Thread.join(timeout)"
        if any(k.arg == "timeout" for k in call.keywords):
            return "Thread.join(timeout=...)"
        return None
    if attr in ("wait", "wait_for"):
        return f".{attr}() (Condition/Event/process wait)"
    if attr == "get":
        if call.args:
            return None  # dict.get / os.environ.get style
        for k in call.keywords:
            if (k.arg == "block" and isinstance(k.value, ast.Constant)
                    and k.value.value is False):
                return None
        return "blocking queue .get()"
    if attr == "put":
        for k in call.keywords:
            if (k.arg == "block" and isinstance(k.value, ast.Constant)
                    and k.value.value is False):
                return None
        if call.args:
            return "blocking queue .put()"
        return None
    if attr == "block_until_ready":
        return ".block_until_ready() (device sync)"
    if attr == "asnumpy":
        return ".asnumpy() (host read, device sync)"
    if attr == "item" and not call.args and not call.keywords:
        return ".item() (host read, device sync)"
    if attr == "lower" and (call.args or call.keywords):
        # jit lowering always takes the example args; str.lower() never
        return ".lower() (jit trace/lower)"
    if attr == "compile":
        return ".compile() (jit compile)"
    return None


class _FuncScanner(ast.NodeVisitor):
    """Pass B: per-function summaries (direct blocking ops, direct lock
    acquisitions, local calls) used by the bounded closure."""

    def __init__(self, src: Source, resolver: "_Resolver",
                 out: Dict[str, _FuncInfo]) -> None:
        self.src = src
        self.resolver = resolver
        self.out = out
        self.cls: List[str] = []
        self.fn: List[_FuncInfo] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls.append(node.name)
        self.generic_visit(node)
        self.cls.pop()

    def _visit_func(self, node) -> None:
        cls = self.cls[-1] if self.cls else None
        qual = (f"{self.src.modname}.{cls}.{node.name}" if cls
                else f"{self.src.modname}.{node.name}")
        info = _FuncInfo(qual=qual, rel=self.src.rel, node=node, cls=cls)
        self.out[qual] = info
        self.fn.append(info)
        self.generic_visit(node)
        self.fn.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_With(self, node: ast.With) -> None:
        if self.fn:
            for item in node.items:
                name = self.resolver.resolve(item.context_expr,
                                             self.src, self.fn[-1].cls)
                if name:
                    self.fn[-1].acquires.append((name, node.lineno))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if self.fn:
            info = self.fn[-1]
            desc = _blocking_desc(node)
            if desc:
                info.blocking.append((node.lineno, desc))
            callee = self.resolver.local_callee(node.func, self.src,
                                                info.cls)
            if callee:
                info.calls.append((callee, node.lineno))
        self.generic_visit(node)


class _Resolver:
    """Map a lock expression / call target to a canonical name."""

    def __init__(self, mods: Dict[str, _ModuleLocks],
                 attr_index: Dict[str, Set[str]],
                 funcs: Dict[str, _FuncInfo]) -> None:
        self.mods = mods
        self.attr_index = attr_index
        self.funcs = funcs

    def resolve(self, expr: ast.AST, src: Source,
                cls: Optional[str]) -> Optional[str]:
        """Resolve to a lock name, following Condition -> underlying."""
        name = self._raw(expr, src, cls)
        if name is None:
            return None
        mod = self.mods.get(src.modname)
        if mod:
            seen = set()
            while name in mod.cond_underlying and name not in seen:
                seen.add(name)
                under = mod.cond_underlying[name]
                resolved = self._raw_dotted(under, src, cls)
                if resolved is None:
                    break
                name = resolved
        return name

    def _raw(self, expr: ast.AST, src: Source,
             cls: Optional[str]) -> Optional[str]:
        if isinstance(expr, ast.Call):
            # with self._lock_for(key): — a lock-returning helper is one
            # lock class per helper
            d = _dotted(expr.func)
            if d and ("lock" in d.rsplit(".", 1)[-1].lower()):
                leaf = d.rsplit(".", 1)[-1]
                if d.startswith("self.") and cls:
                    return f"{src.modname}.{cls}.{leaf}()"
                return f"{src.modname}.{leaf}()"
            return None
        if isinstance(expr, ast.Subscript):
            inner = self._raw(expr.value, src, cls)
            if inner is None:
                return None
            if inner.endswith("[]"):       # lock-list attr resolved
                return inner
            if f"{inner}[]" in self._defs(src):
                return f"{inner}[]"
            return None
        d = _dotted(expr)
        if d is None:
            return None
        return self._raw_dotted(d, src, cls)

    def _raw_dotted(self, d: str, src: Source,
                    cls: Optional[str]) -> Optional[str]:
        defs = self._defs(src)
        if d.startswith("self.") and cls:
            cand = f"{src.modname}.{cls}.{d[5:]}"
            if cand in defs or f"{cand}[]" in defs:
                return cand if cand in defs else f"{cand}[]"
        elif "." not in d:
            cand = f"{src.modname}.{d}"
            if cand in defs:
                return cand
        # foreign attribute (reg.lock): unique attr name across the tree
        leaf = d.rsplit(".", 1)[-1]
        hits = self.attr_index.get(leaf, set())
        if len(hits) == 1:
            return next(iter(hits))
        if len(hits) > 1:
            return f"*.{leaf}"      # ambiguous lock class
        return None

    def _defs(self, src: Source) -> Dict[str, Tuple[str, int]]:
        mod = self.mods.get(src.modname)
        return mod.defs if mod else {}

    def local_callee(self, func: ast.AST, src: Source,
                     cls: Optional[str]) -> Optional[str]:
        if isinstance(func, ast.Name):
            cand = f"{src.modname}.{func.id}"
            return cand if cand in self.funcs else None
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self" and cls):
            cand = f"{src.modname}.{cls}.{func.attr}"
            return cand if cand in self.funcs else None
        return None


def _closure(funcs: Dict[str, _FuncInfo], depth: int = 3
             ) -> Dict[str, Tuple[str, List[Tuple[str, int]]]]:
    """may_block[qual] = (desc, witness chain of (qual, line)).  Bounded
    fixed point over the per-module call graph."""
    may: Dict[str, Tuple[str, List[Tuple[str, int]]]] = {}
    for q, info in funcs.items():
        if info.blocking:
            line, desc = info.blocking[0]
            may[q] = (desc, [(q, line)])
    for _ in range(depth):
        changed = False
        for q, info in funcs.items():
            if q in may:
                continue
            for callee, line in info.calls:
                if callee in may and callee != q:
                    desc, chain = may[callee]
                    # chain entries are (function, line IN that
                    # function): the call line belongs to q, not callee
                    may[q] = (desc, [(q, line)] + chain)
                    changed = True
                    break
        if not changed:
            break
    return may


class _RegionWalker(ast.NodeVisitor):
    """Pass C: walk each function with a held-lock stack; emit MX-L001
    findings and lock-order edges."""

    def __init__(self, src: Source, resolver: _Resolver,
                 funcs: Dict[str, _FuncInfo],
                 may_block: Dict[str, Tuple[str, List[Tuple[str, int]]]],
                 findings: List[Finding],
                 edges: Dict[Tuple[str, str],
                             List[Tuple[str, int]]]) -> None:
        self.src = src
        self.resolver = resolver
        self.funcs = funcs
        self.may_block = may_block
        self.findings = findings
        self.edges = edges
        self.cls: List[str] = []
        # held: (lockname, acquired-src-text)
        self.held: List[Tuple[str, str]] = []

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls.append(node.name)
        self.generic_visit(node)
        self.cls.pop()

    def _visit_func(self, node) -> None:
        saved, self.held = self.held, []
        self.generic_visit(node)
        self.held = saved

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass  # deferred execution: not under the enclosing lock

    def _edge(self, a: str, b: str, line: int) -> None:
        if a != b:
            self.edges.setdefault((a, b), []).append((self.src.rel, line))

    def visit_With(self, node: ast.With) -> None:
        cls = self.cls[-1] if self.cls else None
        pushed = 0
        for item in node.items:
            name = self.resolver.resolve(item.context_expr, self.src, cls)
            if name is None:
                # a non-lock context expression still EVALUATES under
                # whatever locks item(s) to its left already hold —
                # 'with self._lock, closing(sock.accept()[0]):' blocks
                # in the header, not the body
                self.visit(item.context_expr)
            else:
                for held_name, _src in self.held:
                    self._edge(held_name, name, node.lineno)
                try:
                    src_txt = ast.unparse(item.context_expr)
                except Exception:
                    src_txt = ""
                self.held.append((name, src_txt))
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def _wait_releases(self, call: ast.Call) -> Set[int]:
        """Indices in ``self.held`` that a cond.wait() call releases."""
        func = call.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in ("wait", "wait_for")):
            return set()
        cls = self.cls[-1] if self.cls else None
        resolved = self.resolver.resolve(func.value, self.src, cls)
        try:
            recv_src = ast.unparse(func.value)
        except Exception:
            recv_src = None
        out = set()
        for i, (name, src_txt) in enumerate(self.held):
            if (resolved and name == resolved) or (
                    recv_src and src_txt == recv_src):
                out.add(i)
        return out

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            desc = _blocking_desc(node)
            if desc:
                released = self._wait_releases(node)
                held = [n for i, (n, _s) in enumerate(self.held)
                        if i not in released]
                if held:
                    self.findings.append(Finding(
                        "MX-L001", self.src.rel, node.lineno,
                        f"{desc} while holding {', '.join(held)}",
                        "move the blocking call outside the critical "
                        "section (snapshot under the lock, block "
                        "outside), or use a non-blocking variant"))
            else:
                cls = self.cls[-1] if self.cls else None
                callee = self.resolver.local_callee(node.func, self.src,
                                                    cls)
                if callee and callee in self.may_block:
                    bdesc, chain = self.may_block[callee]
                    path = " -> ".join(
                        f"{q.rsplit('.', 1)[-1]}:{ln}" for q, ln in chain)
                    held = [n for n, _s in self.held]
                    self.findings.append(Finding(
                        "MX-L001", self.src.rel, node.lineno,
                        f"call to {callee.rsplit('.', 1)[-1]}() which "
                        f"does {bdesc} (via {path}) while holding "
                        f"{', '.join(held)}",
                        "hoist the blocking work out of the locked "
                        "region or split the callee so the lock is "
                        "dropped first"))
                elif callee:
                    info = self.funcs.get(callee)
                    if info:
                        for lname, ln in info.acquires:
                            for held_name, _s in self.held:
                                self._edge(held_name, lname, node.lineno)
        self.generic_visit(node)


def _cycles(edges: Dict[Tuple[str, str], List[Tuple[str, int]]]
            ) -> List[List[str]]:
    """Strongly connected components of size > 1 (Tarjan, iterative)."""
    adj: Dict[str, List[str]] = {}
    for a, b in edges:
        adj.setdefault(a, []).append(b)
        adj.setdefault(b, [])
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                elif w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                if len(scc) > 1:
                    sccs.append(sorted(scc))

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)
    return sccs


def analyze(ctx: AnalysisContext) -> List[Finding]:
    mods: Dict[str, _ModuleLocks] = {}
    attr_index: Dict[str, Set[str]] = {}
    for src in ctx.sources:
        mod = mods.setdefault(src.modname, _ModuleLocks())
        _DefCollector(src, mod, attr_index).visit(src.tree)

    funcs: Dict[str, _FuncInfo] = {}
    resolver = _Resolver(mods, attr_index, funcs)
    for src in ctx.sources:
        _FuncScanner(src, resolver, funcs).visit(src.tree)
    may_block = _closure(funcs)

    findings: List[Finding] = []
    edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
    for src in ctx.sources:
        _RegionWalker(src, resolver, funcs, may_block, findings,
                      edges).visit(src.tree)

    for scc in _cycles(edges):
        in_cycle = [(e, sites) for e, sites in sorted(edges.items())
                    if e[0] in scc and e[1] in scc]
        if not in_cycle:
            continue
        first_site = in_cycle[0][1][0]
        detail = "; ".join(
            f"{a} -> {b} at {sites[0][0]}:{sites[0][1]}"
            for (a, b), sites in in_cycle)
        findings.append(Finding(
            "MX-L002", first_site[0], first_site[1],
            f"lock-order cycle between {', '.join(scc)}: {detail}",
            "pick one global acquisition order for these locks and "
            "restructure the out-of-order site(s); the runtime "
            "sanitizer (MXNET_SANITIZE=locks) confirms the fix "
            "dynamically"))
    return findings
