"""Network visualization / summaries.

Reference parity (leezu/mxnet): ``python/mxnet/visualization.py`` —
``print_summary`` (layer table with shapes + param counts) and
``plot_network`` (graphviz digraph; gated here since graphviz is not in
the image — the dot source is still produced).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .base import MXNetError
from .symbol.symbol import Symbol, _topo_order

__all__ = ["print_summary", "plot_network"]


def _param_count(shape) -> int:
    n = 1
    for s in shape or ():
        n *= s
    return n if shape else 0


def print_summary(symbol: Symbol,
                  shape: Optional[Dict[str, Tuple[int, ...]]] = None,
                  line_length: int = 98,
                  positions=(0.44, 0.64, 0.74, 1.0)) -> None:
    """Print a Keras-style layer table (reference ``print_summary``)."""
    if not isinstance(symbol, Symbol):
        raise MXNetError("print_summary expects a Symbol")
    shape_dict: Dict[str, Tuple[int, ...]] = {}
    if shape is not None:
        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**shape)
        args = symbol.list_arguments()
        auxs = symbol.list_auxiliary_states()
        shape_dict = dict(zip(args, arg_shapes))
        shape_dict.update(zip(auxs, aux_shapes))
        for name, oshape in zip(symbol.list_outputs(), out_shapes):
            shape_dict[name] = oshape

    order = _topo_order(symbol._heads)
    positions = [int(line_length * p) for p in positions]
    fields = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(cells, pos):
        line = ""
        for c, p in zip(cells, pos):
            line += str(c)
            line = line[:p - 1].ljust(p)
        print(line)

    print("_" * line_length)
    print_row(fields, positions)
    print("=" * line_length)
    total = 0
    for n in order:
        if n.op == "null":
            continue        # params/inputs folded into their consumer row
        # params feeding this node (data inputs — names given in `shape`
        # — are not parameters)
        n_params = 0
        prevs = []
        data_names = set(shape or ())
        for m, _ in n.inputs:
            if m.op == "null":
                if m.name in data_names:
                    prevs.append(m.name)
                else:
                    n_params += _param_count(shape_dict.get(m.name))
            else:
                prevs.append(m.name)
        out_shape = shape_dict.get(f"{n.name}_output", "")
        print_row([f"{n.name} ({n.op})", out_shape or "", n_params,
                   ",".join(prevs)], positions)
        total += n_params
    print("=" * line_length)
    print(f"Total params: {total}")
    print("_" * line_length)


def plot_network(symbol: Symbol, title: str = "plot",
                 save_format: str = "pdf",
                 shape: Optional[Dict[str, Tuple[int, ...]]] = None,
                 node_attrs: Optional[Dict[str, str]] = None,
                 hide_weights: bool = True) -> Any:
    """Build a graphviz Digraph of the network (reference
    ``plot_network``).  Returns the Digraph if the ``graphviz`` package is
    importable, else the dot source string (rendering needs graphviz,
    which this image does not ship)."""
    order = _topo_order(symbol._heads)
    lines = [f'digraph "{title}" {{', "  rankdir=BT;"]
    for n in order:
        if n.op == "null":
            if hide_weights and any(
                    n.uid in (m.uid for m, _ in other.inputs)
                    and other.op != "null" for other in order):
                is_data = not any(
                    n.name.endswith(sfx) for sfx in
                    ("weight", "bias", "gamma", "beta", "moving_mean",
                     "moving_var"))
                if not is_data:
                    continue
            lines.append(
                f'  "{n.name}" [label="{n.name}" shape=oval '
                f'fillcolor="#8dd3c7" style=filled];')
        else:
            lines.append(
                f'  "{n.name}" [label="{n.name}\\n({n.op})" shape=box '
                f'fillcolor="#fb8072" style=filled];')
    for n in order:
        if n.op == "null":
            continue
        for m, _ in n.inputs:
            if m.op == "null" and hide_weights and any(
                    m.name.endswith(sfx) for sfx in
                    ("weight", "bias", "gamma", "beta", "moving_mean",
                     "moving_var")):
                continue
            lines.append(f'  "{m.name}" -> "{n.name}";')
    lines.append("}")
    src = "\n".join(lines)
    try:
        import graphviz
        g = graphviz.Source(src, format=save_format)
        return g
    except ImportError:
        return src
