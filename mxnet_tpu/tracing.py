"""Distributed tracing — sampled spans with cross-wire propagation.

The metrics registry answers "how much / how often" and the profiler
answers "where did time go in one manually-traced window"; this module
answers "why was *this* request slow" and "where did *this* step's 40ms
go".  It is an always-on span runtime in the OpenTelemetry shape but
with zero dependencies and a hot path cheap enough to leave enabled in
production:

* ``span(name, **attrs)`` — context manager *and* decorator.  The first
  span on a thread with no active trace starts one (head-sampled by
  ``MXNET_TRACE_SAMPLE``); nested spans parent automatically through a
  :mod:`contextvars` context.
* finished spans land in a fixed-size ring buffer
  (``MXNET_TRACE_BUFFER_SPANS``) via an atomic-append (one
  ``itertools.count`` fetch + one list-slot store — no lock on the
  record path).
* **tail retention**: a trace that lost the head-sampling coin flip
  still buffers its spans in a small per-trace pending list; if any of
  its spans errors or runs past ``MXNET_TRACE_SLOW_MS`` the whole trace
  is upgraded into the ring buffer.  Slow and failed traces therefore
  survive even 1% sampling — exactly the traces worth keeping.
* **propagation**: the active context rides a contextvar (so ordinary
  calls and nested spans need nothing), and is explicitly attachable
  across threads and queues — ``capture()`` a context where the work is
  submitted, ``attach(ctx)`` where it runs.  The W3C ``traceparent``
  form (``00-<32 hex trace>-<16 hex span>-<2 hex flags>``) crosses the
  HTTP front end and the parameter-server wire, so PS-side handling
  shows up as a remote child span in the worker's trace.
* **export**: :func:`export_trace_events` renders Chrome/Perfetto
  trace-event JSON in the exact shape :func:`mxnet_tpu.profiler.dump`
  writes (same clock epoch, same ``pid``/``tid`` convention), so one
  ``chrome://tracing`` load shows spans and profiled ops side by side.
  Both serving HTTP servers expose it at ``GET /v1/traces``;
  ``tools/trace_dump.py`` fetches or saves it from the CLI.  While the
  profiler is running, finished spans are additionally mirrored
  straight into its event list (category ``"trace"``) through a direct
  append — never through the op-dispatch layer, so spans cannot fire
  monitor hooks or inflate dispatch metrics.

Overhead contract: with ``MXNET_TRACE_SAMPLE=0`` tracing is fully off —
``span()`` returns a shared no-op after one flag read, and zero spans
are ever recorded.  On an untraced path (tracing on, but no active
trace at a child-only site) the cost is one contextvar read.  A
sampled-out span costs a couple of dict/list operations (≤ a few µs).
"""
from __future__ import annotations

import contextlib
import contextvars
import functools
import itertools
import os
import random
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Optional

from .base import getenv, register_env

__all__ = ["span", "child_span", "capture", "attach", "current_context",
           "current_trace_id", "traceparent", "parse_traceparent",
           "record_span", "spans", "export_trace_events",
           "active_spans_tree", "configure", "reset", "SpanContext"]

register_env(
    "MXNET_TRACE_SAMPLE", 1.0,
    "Head-sampling probability per trace for the distributed-tracing "
    "span runtime (mxnet_tpu.tracing). 1.0 records every trace, 0 "
    "disables tracing entirely (spans become no-ops and nothing is "
    "recorded); in between, each new trace keeps its spans with this "
    "probability — except traces containing an error or a span slower "
    "than MXNET_TRACE_SLOW_MS, which are tail-upgraded and kept "
    "regardless.")
register_env(
    "MXNET_TRACE_BUFFER_SPANS", 4096,
    "Capacity of the in-process finished-span ring buffer. Oldest "
    "spans are overwritten; GET /v1/traces, tools/trace_dump.py and "
    "tracing.export_trace_events() export whatever is resident.")
register_env(
    "MXNET_TRACE_SLOW_MS", 100.0,
    "Tail-retention threshold for the span runtime: a span that runs "
    "at least this many milliseconds (or exits with an exception) "
    "upgrades its whole trace into the ring buffer even when the "
    "trace lost the MXNET_TRACE_SAMPLE coin flip, so slow/failed "
    "traces survive low sample rates.")

# spans a not-yet-upgraded trace may hold in its pending list before the
# oldest are dropped (bounds memory for long-lived unsampled traces)
_PENDING_CAP = 256

_CTX: contextvars.ContextVar[Optional["SpanContext"]] = \
    contextvars.ContextVar("mxnet_trace_ctx", default=None)


class _Runtime:
    """Tracing configuration + the ring buffer (rebuilt by configure())."""

    __slots__ = ("sample", "cap", "slow_s", "buf", "seq", "rng")

    def __init__(self, sample: Optional[float] = None,
                 buffer_spans: Optional[int] = None,
                 slow_ms: Optional[float] = None) -> None:
        if sample is None:
            sample = float(getenv("MXNET_TRACE_SAMPLE", 1.0))
        if buffer_spans is None:
            buffer_spans = int(getenv("MXNET_TRACE_BUFFER_SPANS", 4096))
        if slow_ms is None:
            slow_ms = float(getenv("MXNET_TRACE_SLOW_MS", 100.0))
        self.sample = max(0.0, min(1.0, float(sample)))
        self.cap = max(1, int(buffer_spans))
        self.slow_s = max(0.0, float(slow_ms)) / 1e3
        self.buf: List[Optional[Dict[str, Any]]] = [None] * self.cap
        # one atomic fetch per finished span; the slot store is a plain
        # list item assignment — the append path takes no lock
        self.seq = itertools.count()
        self.rng = random.Random(os.urandom(8))


_RT = _Runtime()

# currently-open spans, span_id -> _Span (watchdog dumps walk this)
_OPEN: Dict[str, "_Span"] = {}


def configure(sample: Optional[float] = None,
              buffer_spans: Optional[int] = None,
              slow_ms: Optional[float] = None) -> None:
    """(Re)configure the runtime; unset arguments re-read their env
    vars.  Discards recorded spans (fresh ring buffer)."""
    global _RT
    _RT = _Runtime(sample, buffer_spans, slow_ms)


def reset() -> None:
    """Drop every recorded span (keeps the current configuration)."""
    rt = _RT
    rt.buf = [None] * rt.cap
    rt.seq = itertools.count()


class _TraceState:
    """Mutable per-trace retention state shared by the trace's spans."""

    __slots__ = ("trace_id", "sampled", "upgraded", "dead", "pending",
                 "lock")

    def __init__(self, trace_id: str, sampled: bool) -> None:
        self.trace_id = trace_id
        self.sampled = sampled
        self.upgraded = False
        self.dead = False          # local root ended without retention
        self.pending: List[Dict[str, Any]] = []
        self.lock = threading.Lock()

    @property
    def recording(self) -> bool:
        return self.sampled or self.upgraded


class SpanContext:
    """Immutable propagation handle: (trace_id, span_id, shared state).

    ``capture()`` one where work is submitted; ``attach()`` it where the
    work runs (another thread, a queue consumer); ``traceparent`` is its
    W3C wire form.
    """

    __slots__ = ("trace_id", "span_id", "state")

    def __init__(self, trace_id: str, span_id: str,
                 state: _TraceState) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.state = state

    @property
    def sampled(self) -> bool:
        return self.state.recording

    @property
    def traceparent(self) -> str:
        flags = "01" if self.state.recording else "00"
        return f"00-{self.trace_id}-{self.span_id}-{flags}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return (f"SpanContext(trace={self.trace_id[:8]}…, "
                f"span={self.span_id}, sampled={self.sampled})")


def _emit(rec: Dict[str, Any]) -> None:
    """Commit one finished-span record: ring append + profiler mirror."""
    rt = _RT
    i = next(rt.seq)
    rec["seq"] = i
    rt.buf[i % rt.cap] = rec
    from . import profiler as _prof
    if _prof._active["on"]:
        # direct event append (never via op dispatch: spans must not
        # fire monitor hooks or count as dispatched ops)
        t0 = _prof._P.t0
        _prof.record_span(
            rec["name"], (rec["t_begin"] - t0) * 1e6,
            (rec["t_end"] - t0) * 1e6, rec["tid"],
            {"trace_id": rec["trace_id"], "span_id": rec["span_id"]})


def _upgrade(st: _TraceState) -> None:
    """Tail-based retention: flush the trace's pending spans into the
    ring buffer and record everything that follows directly."""
    with st.lock:
        if st.upgraded:
            return
        st.upgraded = True
        st.dead = False
        pending, st.pending = st.pending, []
    for rec in pending:
        _emit(rec)


def _gen_id(nibbles: int) -> str:
    return f"{_RT.rng.getrandbits(nibbles * 4):0{nibbles}x}"


class _NoopSpan:
    """Shared do-nothing span (tracing off, or child-only miss)."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def __call__(self, fn: Callable) -> Callable:
        return fn

    def add_link(self, trace_id: Optional[str]) -> None:
        pass

    def set_attr(self, **attrs: Any) -> None:
        pass

    trace_id = None
    span_id = None


_NOOP = _NoopSpan()


class _Span:
    """One live span: context manager and decorator."""

    __slots__ = ("name", "attrs", "links", "trace_id", "span_id",
                 "parent_id", "state", "t_begin", "error", "_token",
                 "_root", "_thread")

    def __init__(self, name: str, attrs: Dict[str, Any]) -> None:
        self.name = name
        self.attrs = attrs
        self.links: List[str] = []
        self.error: Optional[str] = None

    # -- decorator form ------------------------------------------------
    def __call__(self, fn: Callable) -> Callable:
        name, attrs = self.name, self.attrs

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            with span(name, **attrs):
                return fn(*args, **kwargs)
        return wrapper

    # -- context-manager form --------------------------------------------
    def __enter__(self) -> "_Span":
        parent = _CTX.get()
        if parent is None:
            rt = _RT
            st = _TraceState(_gen_id(32), rt.rng.random() < rt.sample)
            self.parent_id = ""
            self._root = True
        else:
            st = parent.state
            self.parent_id = parent.span_id
            self._root = False
        self.state = st
        self.trace_id = st.trace_id
        self.span_id = _gen_id(16)
        self._thread = threading.current_thread().name
        self._token = _CTX.set(
            SpanContext(self.trace_id, self.span_id, st))
        self.t_begin = time.perf_counter()
        if not st.dead:
            _OPEN[self.span_id] = self
        return self

    def __exit__(self, et: Any, ev: Any, tb: Any) -> bool:
        t_end = time.perf_counter()
        _CTX.reset(self._token)
        _OPEN.pop(self.span_id, None)
        st = self.state
        if et is not None and self.error is None:
            self.error = f"{getattr(et, '__name__', et)}: {ev}"
        if not st.dead or st.recording:
            rec = self._record(t_end)
            if st.recording:
                _emit(rec)
            elif not st.dead:
                with st.lock:
                    st.pending.append(rec)
                    if len(st.pending) > _PENDING_CAP:
                        del st.pending[0]
                if self.error is not None \
                        or (t_end - self.t_begin) >= _RT.slow_s:
                    _upgrade(st)
        if self._root and not st.recording:
            # trace ended neither sampled nor upgraded: drop it
            st.dead = True
            with st.lock:
                st.pending = []
        return False

    def _record(self, t_end: float) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "name": self.name, "trace_id": self.trace_id,
            "span_id": self.span_id, "parent_id": self.parent_id,
            "t_begin": self.t_begin, "t_end": t_end,
            "tid": threading.get_ident() % 100000,
            "thread": self._thread, "attrs": self.attrs,
        }
        if self.links:
            rec["links"] = list(self.links)
        if self.error is not None:
            rec["status"] = "error"
            rec["error"] = self.error
        else:
            rec["status"] = "ok"
        return rec

    # -- span-local mutation -----------------------------------------------
    def add_link(self, trace_id: Optional[str]) -> None:
        """Link another trace (engine iteration -> resident requests)."""
        if trace_id:
            self.links.append(trace_id)

    def set_attr(self, **attrs: Any) -> None:
        self.attrs.update(attrs)


def span(name: str, **attrs: Any):
    """Open a span: ``with span("prefill", req=rid): ...`` or
    ``@span("checkpoint.save")``.  With no active trace this starts a
    new head-sampled one; nested calls parent automatically."""
    if _RT.sample <= 0.0:
        return _NOOP
    return _Span(name, attrs)


def child_span(name: str, **attrs: Any):
    """Like :func:`span` but never *starts* a trace: a no-op unless a
    trace is already active.  For hot internal sites (bulk flushes, kv
    wire ops) that should appear inside request/step traces without
    minting a trace of their own per call."""
    if _RT.sample <= 0.0 or _CTX.get() is None:
        return _NOOP
    return _Span(name, attrs)


def record_span(name: str, begin: float, end: float,
                ctx: Optional[SpanContext] = None,
                **attrs: Any) -> None:
    """Emit a span for an interval measured elsewhere (queue waits:
    begin/end are ``time.perf_counter()`` values).  ``ctx`` parents it;
    with ``ctx=None`` the currently-attached context is used, and with
    no trace active at all it is dropped."""
    if _RT.sample <= 0.0:
        return
    if ctx is None:
        ctx = _CTX.get()
    if ctx is None:
        return
    st = ctx.state
    if st.dead and not st.recording:
        return
    rec: Dict[str, Any] = {
        "name": name, "trace_id": ctx.trace_id,
        "span_id": _gen_id(16), "parent_id": ctx.span_id,
        "t_begin": begin, "t_end": end,
        "tid": threading.get_ident() % 100000,
        "thread": threading.current_thread().name,
        "attrs": attrs, "status": "ok",
    }
    if st.recording:
        _emit(rec)
    else:
        with st.lock:
            st.pending.append(rec)
            if len(st.pending) > _PENDING_CAP:
                del st.pending[0]
        if (end - begin) >= _RT.slow_s:
            _upgrade(st)


# ---------------------------------------------------------------------------
# context propagation
# ---------------------------------------------------------------------------

def current_context() -> Optional[SpanContext]:
    """The active span's context (None when untraced)."""
    return _CTX.get()


def current_trace_id() -> Optional[str]:
    """Trace id of the active trace — metric exemplars pass this."""
    ctx = _CTX.get()
    return ctx.trace_id if ctx is not None else None


def capture() -> Optional[SpanContext]:
    """Snapshot the active context for an explicit hand-off (store it
    on the queue item / request object at submit time)."""
    return _CTX.get()


@contextlib.contextmanager
def attach(ctx: Optional[SpanContext]) -> Iterator[None]:
    """Run the body under ``ctx`` (a :func:`capture` snapshot or a
    :func:`parse_traceparent` result).  ``attach(None)`` is a no-op, so
    call sites need no conditional."""
    if ctx is None:
        yield
        return
    token = _CTX.set(ctx)
    try:
        yield
    finally:
        _CTX.reset(token)


def traceparent() -> Optional[str]:
    """W3C ``traceparent`` header for the active context, or None."""
    ctx = _CTX.get()
    return ctx.traceparent if ctx is not None else None


def parse_traceparent(header: Optional[str]) -> Optional[SpanContext]:
    """Parse a ``00-<trace>-<span>-<flags>`` header into an attachable
    remote context (spans opened under it become remote children).
    Malformed input — or tracing off — returns None."""
    if not header or _RT.sample <= 0.0:
        return None
    parts = header.strip().lower().split("-")
    if len(parts) != 4:
        return None
    ver, tid, sid, flags = parts
    if len(ver) != 2 or len(tid) != 32 or len(sid) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(tid, 16), int(sid, 16), int(flags, 16)
    except ValueError:
        return None
    if int(tid, 16) == 0 or int(sid, 16) == 0:
        return None
    st = _TraceState(tid, bool(int(flags, 16) & 1))
    return SpanContext(tid, sid, st)


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def spans(trace_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Recorded spans, oldest first (optionally one trace's)."""
    rt = _RT
    out = [r for r in list(rt.buf) if r is not None]
    if trace_id is not None:
        out = [r for r in out if r["trace_id"] == trace_id]
    out.sort(key=lambda r: r["seq"])
    return out


def export_trace_events() -> Dict[str, Any]:
    """Chrome/Perfetto trace-event JSON — byte-shape identical to the
    profiler's :func:`mxnet_tpu.profiler.dump` payload and on the same
    clock epoch, so one ``chrome://tracing`` / Perfetto load can show a
    profiler dump and this export side by side."""
    from . import profiler as _prof
    t0 = _prof._P.t0
    events: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "mxnet_tpu"}},
    ]
    for rec in spans():
        args: Dict[str, Any] = {
            "trace_id": rec["trace_id"], "span_id": rec["span_id"],
            "parent_id": rec["parent_id"], "status": rec["status"],
            "thread": rec["thread"],
        }
        if rec.get("error"):
            args["error"] = rec["error"]
        if rec.get("links"):
            args["links"] = rec["links"]
        for k, v in rec["attrs"].items():
            args.setdefault(k, v if isinstance(
                v, (int, float, bool, str, type(None))) else str(v))
        events.append({
            "name": rec["name"], "cat": "trace", "ph": "X",
            "ts": (rec["t_begin"] - t0) * 1e6,
            "dur": max(0.0, (rec["t_end"] - rec["t_begin"]) * 1e6),
            "pid": 0, "tid": rec["tid"], "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def active_spans_tree() -> List[str]:
    """The currently-open spans as indented text lines, grouped by
    trace — the hang watchdog appends this to its diagnostic dump so a
    stall names the span it wedged in.  Never raises."""
    try:
        now = time.perf_counter()
        open_spans = [s for s in list(_OPEN.values())
                      if getattr(s, "span_id", None) is not None]
        by_id = {s.span_id: s for s in open_spans}
        children: Dict[str, List[_Span]] = {}
        roots: List[_Span] = []
        for s in open_spans:
            if s.parent_id and s.parent_id in by_id:
                children.setdefault(s.parent_id, []).append(s)
            else:
                roots.append(s)
        roots.sort(key=lambda s: (s.trace_id, s.t_begin))
        lines: List[str] = []

        def walk(s: "_Span", depth: int) -> None:
            age_ms = (now - s.t_begin) * 1e3
            attrs = " ".join(f"{k}={v}" for k, v in s.attrs.items())
            lines.append(
                f"{'  ' * depth}{s.name} trace={s.trace_id[:8]} "
                f"span={s.span_id[:8]} +{age_ms:.0f}ms "
                f"thread={s._thread}" + (f" {attrs}" if attrs else ""))
            for c in sorted(children.get(s.span_id, []),
                            key=lambda x: x.t_begin):
                walk(c, depth + 1)

        for r in roots:
            walk(r, 0)
        return lines
    except Exception:   # noqa: BLE001 - diagnostics must never raise
        return []
