"""Symbol naming scopes.

Reference parity (leezu/mxnet): ``python/mxnet/name.py`` — ``NameManager``
(auto-naming of unnamed symbols) and ``Prefix`` (prepends a prefix inside
a ``with`` block).
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

__all__ = ["NameManager", "Prefix"]


class _Current(threading.local):
    def __init__(self) -> None:
        self.manager: Optional["NameManager"] = None


_CURRENT = _Current()


class NameManager:
    """Assigns ``op0``, ``op1``, … names to unnamed symbols; use as a
    context manager to scope the counter."""

    def __init__(self) -> None:
        self._counter: Dict[str, int] = {}
        self._old: Optional[NameManager] = None

    def get(self, name: Optional[str], hint: str) -> str:
        if name:
            return name
        n = self._counter.get(hint, 0)
        self._counter[hint] = n + 1
        return f"{hint}{n}"

    @staticmethod
    def current() -> "NameManager":
        """The active manager, or the process-wide default — never
        installs anything (symbol auto-naming shares the default's
        counter, so observing must not fork the namespace)."""
        return _CURRENT.manager if _CURRENT.manager is not None \
            else _DEFAULT

    def __enter__(self) -> "NameManager":
        self._old = _CURRENT.manager
        _CURRENT.manager = self
        return self

    def __exit__(self, *exc) -> None:
        _CURRENT.manager = self._old


# process-wide default namespace (the symbol layer's auto-name counter)
_DEFAULT = NameManager()


class Prefix(NameManager):
    """NameManager that prepends ``prefix`` to every generated name
    (reference ``mx.name.Prefix``)."""

    def __init__(self, prefix: str) -> None:
        super().__init__()
        self._prefix = prefix

    def get(self, name: Optional[str], hint: str) -> str:
        return self._prefix + super().get(name, hint)
