"""Autograd — ``record`` / ``pause`` / ``backward`` / ``grad`` / ``Function``.

Reference parity (leezu/mxnet): ``python/mxnet/autograd.py`` over the C API
``MXAutograd*`` functions, backed by ``src/imperative/imperative.cc``. Tape
internals live in ``mxnet_tpu/_tape.py`` (vjp-based TapeNodes instead of
NNVM gradient subgraphs).
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ._tape import (TapeNode, backward_arrays, is_recording, is_training,
                    set_recording, set_training)
from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "set_recording", "set_training", "backward",
           "grad", "mark_variables", "Function"]


class _RecordingStateScope:
    """Scope that sets recording/training flags and restores them on exit."""

    def __init__(self, is_record: Optional[bool], train: Optional[bool]) -> None:
        self._enter_record = is_record
        self._enter_train = train
        self._prev_record: Optional[bool] = None
        self._prev_train: Optional[bool] = None

    def __enter__(self) -> None:
        if self._enter_record is not None:
            self._prev_record = set_recording(self._enter_record)
        if self._enter_train is not None:
            self._prev_train = set_training(self._enter_train)

    def __exit__(self, *exc: Any) -> None:
        if self._prev_record is not None:
            set_recording(self._prev_record)
        if self._prev_train is not None:
            set_training(self._prev_train)


def record(train_mode: bool = True) -> _RecordingStateScope:  # noqa: D401
    """Scope recording ops onto the autograd tape (``autograd.record``)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False) -> _RecordingStateScope:
    """Scope suspending recording (``autograd.pause``)."""
    return _RecordingStateScope(False, train_mode)


def train_mode() -> _RecordingStateScope:
    """Scope forcing training behavior of ops (dropout active)."""
    return _RecordingStateScope(None, True)


def predict_mode() -> _RecordingStateScope:
    """Scope forcing inference behavior of ops."""
    return _RecordingStateScope(None, False)


def mark_variables(variables: Sequence[NDArray],
                   gradients: Sequence[NDArray],
                   grad_reqs: Union[str, Sequence[str]] = "write") -> None:
    """Attach gradient buffers to variables (``MXAutogradMarkVariables``)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad_req = req
        v._grad = g


def _as_list(x: Any) -> List[Any]:
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def backward(heads: Union[NDArray, Sequence[NDArray]],
             head_grads: Optional[Union[NDArray, Sequence[Optional[NDArray]]]] = None,
             retain_graph: bool = False, train_mode: bool = True) -> None:
    """Compute gradients of ``heads`` w.r.t. attached variables."""
    heads = _as_list(heads)
    head_grads = _as_list(head_grads) if head_grads is not None else None
    backward_arrays(heads, head_grads, retain_graph=retain_graph)


def grad(heads: Union[NDArray, Sequence[NDArray]],
         variables: Union[NDArray, Sequence[NDArray]],
         head_grads: Optional[Sequence[NDArray]] = None,
         retain_graph: Optional[bool] = None, create_graph: bool = False,
         train_mode: bool = True) -> Union[NDArray, List[NDArray]]:
    """Return gradients of heads w.r.t. ``variables`` (``autograd.grad``)."""
    if create_graph:
        raise MXNetError(
            "create_graph=True (higher-order imperative autograd) is not "
            "supported; differentiate a hybridized block instead, where "
            "arbitrary-order gradients compose through jax.grad")
    single = isinstance(variables, NDArray)
    heads_l = _as_list(heads)
    vars_l = _as_list(variables)
    retain = retain_graph if retain_graph is not None else create_graph
    raws = backward_arrays(heads_l,
                           _as_list(head_grads) if head_grads is not None else None,
                           retain_graph=retain, variables=vars_l)
    outs = [NDArray(r, _wrap=True) for r in raws]
    return outs[0] if single else outs


def get_symbol(x: NDArray) -> None:
    raise MXNetError("symbol extraction from the tape is not supported; use "
                     "HybridBlock.export for a serialized graph")


class Function:
    """Custom differentiable function with user-defined backward.

    Reference parity: ``mxnet.autograd.Function`` (CustomFunction op).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` operating on NDArrays; call the
    instance to apply it.
    """

    def __init__(self) -> None:
        self._saved: tuple = ()

    def save_for_backward(self, *arrays: NDArray) -> None:
        self._saved = arrays

    @property
    def saved_tensors(self) -> tuple:
        return self._saved

    def forward(self, *inputs: NDArray) -> Any:
        raise NotImplementedError

    def backward(self, *output_grads: NDArray) -> Any:
        raise NotImplementedError

    def __call__(self, *inputs: NDArray) -> Any:
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)

        if is_recording() and any(x._on_tape for x in inputs
                                  if isinstance(x, NDArray)):
            fn = self

            def vjp_fn(cots: Any) -> tuple:
                cot_list = [cots] if single else list(cots)
                with pause():
                    in_grads = fn.backward(
                        *[NDArray(c, _wrap=True) for c in cot_list])
                if not isinstance(in_grads, (list, tuple)):
                    in_grads = [in_grads]
                return tuple(g._data if isinstance(g, NDArray) else g
                             for g in in_grads)

            nd_inputs = [x for x in inputs if isinstance(x, NDArray)]
            avals = [(o.shape, o.dtype) for o in outs]
            node = TapeNode(type(self).__name__, vjp_fn, nd_inputs, avals)
            import weakref
            node.out_arrays = [weakref.ref(o) for o in outs]
            for i, o in enumerate(outs):
                o._ag_node = node
                o._ag_out_idx = i
        return outs[0] if single else tuple(outs)
