"""``mx.operator`` — user-defined custom operators in Python.

Reference parity (leezu/mxnet): ``python/mxnet/operator.py`` +
``src/operator/custom/custom.cc`` — ``CustomOp``/``CustomOpProp`` classes,
``mx.operator.register`` decorator, invoked as
``mx.nd.Custom(*data, op_type=name)``.

Design (tpu-first): the reference re-enters the engine from a dedicated
callback thread pool; here custom ops run eagerly on host at dispatch time
(they are by definition opaque Python, so they are a host boundary — the
same position they occupy in the reference's schedule).  Gradients plug
into the autograd tape through the custom-vjp hook, so ``backward`` composes
with the rest of the tape exactly like a built-in op.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as _np

from .base import MXNetError
from .ndarray import ops as ndops
from .ndarray.ndarray import NDArray, from_jax
from .ndarray.register import (invoke_with_custom_vjp, is_recording,
                               register_op)

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_REGISTRY: Dict[str, type] = {}


class CustomOp:
    """Base for user ops (reference: ``mx.operator.CustomOp``)."""

    def forward(self, is_train: bool, req: Sequence[str],
                in_data: Sequence[NDArray], out_data: List[Optional[NDArray]],
                aux: Sequence[NDArray]) -> None:
        raise NotImplementedError

    def backward(self, req: Sequence[str], out_grad: Sequence[NDArray],
                 in_data: Sequence[NDArray], out_data: Sequence[NDArray],
                 in_grad: List[Optional[NDArray]],
                 aux: Sequence[NDArray]) -> None:
        raise NotImplementedError

    def assign(self, dst: Any, index_or_req: Any, src: Any,
               req: str = "write") -> None:
        """Write ``src`` into an output/grad slot.

        Both conventions work: the reference's
        ``self.assign(out_data[0], req[0], result)`` (out_data entries are
        preallocated NDArrays, written in place) and the list form
        ``self.assign(out_data, req[0], result)`` (writes slot 0)."""
        val = src if isinstance(src, NDArray) else ndops.array(src)
        if isinstance(dst, list):
            if isinstance(index_or_req, int):
                idx, mode = index_or_req, req
            else:
                idx, mode = 0, index_or_req
            if mode == "null":
                return
            if mode == "add_to" and dst[idx] is not None:
                dst[idx] = dst[idx] + val
            else:
                dst[idx] = val
        elif isinstance(dst, NDArray):
            mode = index_or_req if isinstance(index_or_req, str) else req
            if mode == "null":
                return
            if mode == "add_to":
                dst._data = (dst + val)._data
            else:
                dst._data = val._data.astype(dst.dtype) \
                    if val.dtype != dst.dtype else val._data
        else:
            raise MXNetError("assign expects an NDArray slot or the "
                             "out_data/in_grad list")


class CustomOpProp:
    """Op metadata + factory (reference: ``mx.operator.CustomOpProp``)."""

    def __init__(self, need_top_grad: bool = True) -> None:
        self.need_top_grad_ = need_top_grad

    def list_arguments(self) -> List[str]:
        return ["data"]

    def list_outputs(self) -> List[str]:
        return ["output"]

    def list_auxiliary_states(self) -> List[str]:
        return []

    def infer_shape(self, in_shape: Sequence[Tuple[int, ...]]):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type: Sequence[Any]):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx: Any, in_shapes: Sequence[Tuple[int, ...]],
                        in_dtypes: Sequence[Any]) -> CustomOp:
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(op_type: str) -> Callable[[type], type]:
    """Register a CustomOpProp subclass under ``op_type``
    (reference: ``mx.operator.register``)."""

    def wrap(prop_cls: type) -> type:
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError("register expects a CustomOpProp subclass")
        _REGISTRY[op_type] = prop_cls
        return prop_cls
    return wrap


def get_all_registered() -> List[str]:
    return sorted(_REGISTRY)


def _invoke_custom(op_type: str, inputs: Sequence[NDArray],
                   kwargs: Dict[str, Any]) -> Any:
    if op_type not in _REGISTRY:
        raise MXNetError(f"custom op {op_type!r} is not registered; "
                         f"known: {get_all_registered()}")
    prop = _REGISTRY[op_type](**kwargs)
    n_args = len(prop.list_arguments())
    n_aux = len(prop.list_auxiliary_states())
    if len(inputs) != n_args + n_aux:
        raise MXNetError(
            f"custom op {op_type!r} expects {n_args} args + {n_aux} aux, "
            f"got {len(inputs)} inputs")
    in_data = list(inputs[:n_args])
    aux = list(inputs[n_args:])

    in_shapes = [tuple(x.shape) for x in in_data]
    in_dtypes = [x.dtype for x in in_data]
    _, out_shapes, _ = prop.infer_shape(in_shapes)
    _, out_dtypes, _ = prop.infer_type(in_dtypes)
    op = prop.create_operator(None, in_shapes, in_dtypes)

    n_out = len(prop.list_outputs())
    # preallocate outputs so the reference's assign(out_data[0], ...)
    # convention works; the list convention may replace entries
    out_data: List[Optional[NDArray]] = [
        ndops.zeros(tuple(s), dtype=_np.dtype(dt).name)
        for s, dt in zip(out_shapes, out_dtypes)]
    req = ["write"] * n_out

    recording = is_recording() and any(x._on_tape for x in in_data)
    op.forward(recording, req, in_data, out_data, aux)
    for i, o in enumerate(out_data):
        if o is None:
            raise MXNetError(f"custom op {op_type!r} did not assign "
                             f"output {i}")
        if tuple(o.shape) != tuple(out_shapes[i]):
            raise MXNetError(
                f"custom op {op_type!r} output {i} has shape "
                f"{tuple(o.shape)} but infer_shape declared "
                f"{tuple(out_shapes[i])}")

    if not recording:
        return out_data[0] if n_out == 1 else tuple(out_data)

    if n_out != 1:
        raise MXNetError("autograd through multi-output custom ops is not "
                         "supported; wrap outputs in separate ops")

    result = out_data[0]

    def vjp_fn(out_cot):
        ograd = from_jax(out_cot)
        # preallocated zero grads: both assign conventions work, and an
        # unassigned slot correctly means zero gradient
        in_grad: List[Optional[NDArray]] = [
            ndops.zeros(tuple(x.shape), dtype=_np.dtype(x.dtype).name)
            for x in in_data]
        op.backward(["write"] * n_args, [ograd], in_data, out_data,
                    in_grad, aux)
        cots = []
        for g in in_grad:
            cots.append(None if g is None else g._data)
        return cots + [None] * n_aux

    # re-run forward under the tape's custom-vjp hook so the output is a
    # tracked NDArray whose pullback calls op.backward
    def impl(*arrays):
        return result._data

    return invoke_with_custom_vjp(f"Custom[{op_type}]", impl,
                                  list(in_data) + list(aux), vjp_fn)


def Custom(*data: NDArray, op_type: str, **kwargs: Any) -> Any:
    """Invoke a registered custom op (reference: ``mx.nd.Custom``)."""
    return _invoke_custom(op_type, list(data), kwargs)


register_op("Custom", Custom)
