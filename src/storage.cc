/*
 * storage.cc — pooled host storage manager.
 *
 * Reference parity (leezu/mxnet): src/storage/storage.cc,
 * src/storage/pooled_storage_manager.h (GPUPooledStorageManager with
 * round-up buckets, MXNET_GPU_MEM_POOL_TYPE=Round).  Device memory on TPU
 * belongs to PJRT/XLA; this pool serves the host side: RecordIO read
 * buffers, prefetcher batches, staging space for checkpoint IO — the
 * role CPUSharedStorage/pinned memory plays in the reference's data
 * pipeline.
 *
 * Strategy: sizes are rounded up to the next power of two (>= 4KiB uses
 * pow2 buckets; small sizes round to 64B lines) and freed blocks are
 * cached in per-bucket free lists, bounded by MXTPU_MEM_POOL_LIMIT bytes
 * (default 1GiB) of cached memory.
 */
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "./mxtpu.h"

namespace mxtpu {

void SetLastError(const std::string &msg);

namespace {

constexpr size_t kAlign = 64;

size_t RoundSize(size_t size) {
  if (size <= kAlign) return kAlign;
  if (size < 4096) return (size + kAlign - 1) & ~(kAlign - 1);
  size_t p = 4096;
  while (p < size) p <<= 1;
  return p;
}

class Pool {
 public:
  static Pool &Get() {
    static Pool inst;
    return inst;
  }

  void *Alloc(size_t size) {
    size_t bucket = RoundSize(size);
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = free_.find(bucket);
      if (it != free_.end() && !it->second.empty()) {
        void *p = it->second.back();
        it->second.pop_back();
        pooled_bytes_ -= bucket;
        in_use_bytes_ += bucket;
        ++hits_;
        sizes_[p] = bucket;
        return p;
      }
      ++misses_;
    }
    void *p = nullptr;
    if (posix_memalign(&p, kAlign, bucket) != 0 || p == nullptr) {
      /* Reference behavior: on OOM, release the pool and retry once
       * (GPUPooledStorageManager::Alloc → ReleaseAll → retry). */
      ReleaseAll();
      if (posix_memalign(&p, kAlign, bucket) != 0 || p == nullptr) {
        throw std::bad_alloc();
      }
    }
    std::lock_guard<std::mutex> lk(mu_);
    in_use_bytes_ += bucket;
    sizes_[p] = bucket;
    return p;
  }

  void Free(void *ptr) {
    size_t bucket;
    {
      std::lock_guard<std::mutex> lk(mu_);
      auto it = sizes_.find(ptr);
      if (it == sizes_.end()) {
        throw std::runtime_error("MXStorageFree: unknown pointer");
      }
      bucket = it->second;
      sizes_.erase(it);
      in_use_bytes_ -= bucket;
      if (pooled_bytes_ + bucket <= PoolLimit()) {
        free_[bucket].push_back(ptr);
        pooled_bytes_ += bucket;
        return;
      }
    }
    std::free(ptr);
  }

  void ReleaseAll() {
    std::lock_guard<std::mutex> lk(mu_);
    for (auto &kv : free_) {
      for (void *p : kv.second) std::free(p);
      kv.second.clear();
    }
    pooled_bytes_ = 0;
  }

  void Stats(uint64_t *in_use, uint64_t *pooled, uint64_t *hits,
             uint64_t *misses) {
    std::lock_guard<std::mutex> lk(mu_);
    *in_use = in_use_bytes_;
    *pooled = pooled_bytes_;
    *hits = hits_;
    *misses = misses_;
  }

 private:
  static size_t PoolLimit() {
    static size_t limit = [] {
      const char *env = std::getenv("MXTPU_MEM_POOL_LIMIT");
      return env ? static_cast<size_t>(std::atoll(env))
                 : (size_t)1 << 30;
    }();
    return limit;
  }

  std::mutex mu_;
  std::unordered_map<size_t, std::vector<void *>> free_;
  std::unordered_map<void *, size_t> sizes_;
  uint64_t in_use_bytes_ = 0;
  uint64_t pooled_bytes_ = 0;
  uint64_t hits_ = 0;
  uint64_t misses_ = 0;
};

}  // namespace

void *PoolAlloc(size_t size) { return Pool::Get().Alloc(size); }
void PoolFree(void *ptr) { Pool::Get().Free(ptr); }

}  // namespace mxtpu

#define API_BEGIN() try {
#define API_END()                        \
  }                                      \
  catch (const std::exception &e) {      \
    mxtpu::SetLastError(e.what());       \
    return -1;                           \
  }                                      \
  return 0;

extern "C" {

int MXStorageAlloc(size_t size, void **out) {
  API_BEGIN();
  *out = mxtpu::PoolAlloc(size);
  API_END();
}

int MXStorageFree(void *ptr) {
  API_BEGIN();
  mxtpu::PoolFree(ptr);
  API_END();
}

int MXStorageReleaseAll(void) {
  API_BEGIN();
  mxtpu::Pool::Get().ReleaseAll();
  API_END();
}

int MXStorageStats(uint64_t *bytes_in_use, uint64_t *bytes_pooled,
                   uint64_t *pool_hits, uint64_t *pool_misses) {
  API_BEGIN();
  mxtpu::Pool::Get().Stats(bytes_in_use, bytes_pooled, pool_hits,
                           pool_misses);
  API_END();
}

}  // extern "C"
