/*
 * c_api.cc — error trampoline + runtime feature introspection.
 *
 * Reference parity (leezu/mxnet): src/c_api/c_api_error.cc
 * (MXGetLastError with thread-local storage; every API function returns
 * -1 and stores the message) and src/libinfo.cc (runtime feature flags
 * surfaced as mx.runtime.Features).
 */
#include <string>

#include "./mxtpu.h"

namespace mxtpu {

namespace {
thread_local std::string last_error;
}

void SetLastError(const std::string &msg) { last_error = msg; }

}  // namespace mxtpu

extern "C" {

const char *MXGetLastError(void) { return mxtpu::last_error.c_str(); }

const char *MXLibInfoFeatures(void) {
  /* comma-separated feature names; the Python side pairs this with
   * jax-derived features (TPU, etc.) in mxnet_tpu.runtime */
  return "NATIVE_ENGINE,NATIVE_STORAGE_POOL,NATIVE_RECORDIO,"
         "NATIVE_PREFETCHER,CHROME_TRACE_PROFILER,NATIVE_NDARRAY,"
         "PARAMS_IO";
}

}  // extern "C"
