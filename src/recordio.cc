/*
 * recordio.cc — RecordIO reader/writer + threaded prefetcher.
 *
 * Reference parity (leezu/mxnet):
 * 3rdparty/dmlc-core/include/dmlc/recordio.h (framing),
 * src/io/iter_prefetcher.h + dmlc/threadediter.h (double-buffered
 * background producer).  Format is byte-identical to the reference (and
 * to python/mxnet_tpu/recordio.py):
 *
 *   record  := magic:u32 (0xced7230a) | lrecord:u32 | data | pad to 4B
 *   lrecord := cflag:u3 << 29 | length:u29
 */
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "./mxtpu.h"

namespace mxtpu {

void SetLastError(const std::string &msg);
void *PoolAlloc(size_t size);
void PoolFree(void *ptr);

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

class Writer {
 public:
  explicit Writer(const char *path) : fp_(std::fopen(path, "wb")) {
    if (!fp_) throw std::runtime_error(std::string("cannot open ") + path);
  }
  ~Writer() {
    if (fp_) std::fclose(fp_);
  }

  uint64_t Write(const char *data, uint64_t size) {
    if (size > kLenMask) throw std::runtime_error("record too large");
    uint64_t pos = static_cast<uint64_t>(std::ftell(fp_));
    uint32_t head[2] = {kMagic, static_cast<uint32_t>(size)};
    if (std::fwrite(head, 1, 8, fp_) != 8) {
      throw std::runtime_error("short write");
    }
    if (size && std::fwrite(data, 1, size, fp_) != size) {
      throw std::runtime_error("short write");
    }
    size_t pad = (4 - ((8 + size) % 4)) % 4;
    if (pad) {
      const char zeros[4] = {0, 0, 0, 0};
      if (std::fwrite(zeros, 1, pad, fp_) != pad) {
        throw std::runtime_error("short write");
      }
    }
    return pos;
  }

  uint64_t Tell() { return static_cast<uint64_t>(std::ftell(fp_)); }

 private:
  std::FILE *fp_;
};

class Reader {
 public:
  explicit Reader(const char *path) : fp_(std::fopen(path, "rb")) {
    if (!fp_) throw std::runtime_error(std::string("cannot open ") + path);
  }
  ~Reader() {
    if (fp_) std::fclose(fp_);
    if (buf_) PoolFree(buf_);
  }

  /* Returns pointer into internal buffer, or nullptr at EOF. */
  const char *Next(uint64_t *out_size) {
    uint32_t head[2];
    size_t got = std::fread(head, 1, 8, fp_);
    if (got < 8) {
      if (got == 0) return nullptr;
      throw std::runtime_error("truncated record header");
    }
    if (head[0] != kMagic) throw std::runtime_error("bad record magic");
    uint64_t length = head[1] & kLenMask;
    Reserve(length);
    if (length && std::fread(buf_, 1, length, fp_) != length) {
      throw std::runtime_error("truncated record body");
    }
    size_t pad = (4 - ((8 + length) % 4)) % 4;
    if (pad) {
      char scratch[4];
      if (std::fread(scratch, 1, pad, fp_) != pad) {
        throw std::runtime_error("truncated record padding");
      }
    }
    *out_size = length;
    return buf_;
  }

  void Seek(uint64_t pos) {
    if (std::fseek(fp_, static_cast<long>(pos), SEEK_SET) != 0) {
      throw std::runtime_error("seek failed");
    }
  }

  uint64_t Tell() { return static_cast<uint64_t>(std::ftell(fp_)); }

  std::vector<uint64_t> ScanIndex() {
    Seek(0);
    std::vector<uint64_t> positions;
    uint64_t size;
    for (;;) {
      uint64_t pos = Tell();
      if (Next(&size) == nullptr) break;
      positions.push_back(pos);
    }
    Seek(0);
    return positions;
  }

 private:
  void Reserve(uint64_t length) {
    if (length <= cap_) return;
    if (buf_) PoolFree(buf_);
    // clear before realloc: if PoolAlloc throws, ~Reader must not
    // double-free the old pointer
    buf_ = nullptr;
    cap_ = 0;
    buf_ = static_cast<char *>(PoolAlloc(length));
    cap_ = length;
  }

  std::FILE *fp_;
  char *buf_ = nullptr;
  uint64_t cap_ = 0;
};

/* Threaded prefetcher: a producer thread reads batches of records ahead
 * of the consumer, bounded by `capacity` in-flight batches. */
class Prefetcher {
 public:
  Prefetcher(const char *path, int batch_size, int capacity,
             const uint64_t *index, uint64_t index_len)
      : path_(path), batch_(batch_size),
        capacity_(capacity > 0 ? capacity : 2) {
    if (index && index_len) {
      index_.assign(index, index + index_len);
    }
    Start();
  }

  ~Prefetcher() { Stop(); }

  struct Batch {
    /* one pooled buffer holding all records, plus offsets/sizes */
    char *data = nullptr;
    std::vector<uint64_t> offsets;
    std::vector<uint64_t> sizes;
    int n = 0;
    bool epoch_end = false;
  };

  /* Blocks until a batch is available.  Caller owns `last_` until the
   * next call. */
  Batch *Next() {
    std::unique_lock<std::mutex> lk(mu_);
    cv_nonempty_.wait(lk, [this] {
      return !queue_.empty() || error_set_ || finished_;
    });
    if (queue_.empty()) {
      if (error_set_) throw std::runtime_error(error_);
      /* producer exhausted and queue drained: keep returning the
       * epoch-end marker instead of blocking forever */
      FreeLast();
      last_ = new Batch();
      last_->epoch_end = true;
      return last_;
    }
    FreeLast();
    last_ = queue_.front();
    queue_.pop_front();
    cv_nonfull_.notify_one();
    return last_;
  }

  void Reset() {
    Stop();
    Start();
  }

 private:
  void Start() {
    stop_ = false;
    finished_ = false;
    error_set_ = false;
    error_.clear();
    producer_ = std::thread([this] { ProducerLoop(); });
  }

  void Stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_nonfull_.notify_all();
    if (producer_.joinable()) producer_.join();
    std::lock_guard<std::mutex> lk(mu_);
    FreeLast();
    for (Batch *b : queue_) {
      if (b->data) PoolFree(b->data);
      delete b;
    }
    queue_.clear();
  }

  void FreeLast() {
    if (last_) {
      if (last_->data) PoolFree(last_->data);
      delete last_;
      last_ = nullptr;
    }
  }

  /* Returns false if the prefetcher is shutting down. */
  bool Enqueue(Batch *b) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_nonfull_.wait(lk, [this] {
      return stop_ || static_cast<int>(queue_.size()) < capacity_;
    });
    if (stop_) {
      if (b->data) PoolFree(b->data);
      delete b;
      return false;
    }
    queue_.push_back(b);
    cv_nonempty_.notify_one();
    return true;
  }

  void ProducerLoop() {
    try {
      Reader reader(path_.c_str());
      size_t cursor = 0; /* into index_, when present */
      bool done = false;
      while (!done) {
        Batch *b = new Batch();
        std::vector<std::string> recs;
        uint64_t total = 0;
        for (int i = 0; i < batch_; ++i) {
          const char *data = nullptr;
          uint64_t size = 0;
          if (!index_.empty()) {
            if (cursor >= index_.size()) break;
            reader.Seek(index_[cursor++]);
            data = reader.Next(&size);
          } else {
            data = reader.Next(&size);
          }
          if (!data) break;
          recs.emplace_back(data, size);
          total += size;
        }
        b->n = static_cast<int>(recs.size());
        if (b->n < batch_) {
          done = true;
          b->epoch_end = true;
        }
        if (b->n > 0) {
          b->data = static_cast<char *>(PoolAlloc(total ? total : 1));
          uint64_t off = 0;
          for (const std::string &r : recs) {
            std::memcpy(b->data + off, r.data(), r.size());
            b->offsets.push_back(off);
            b->sizes.push_back(r.size());
            off += r.size();
          }
        }
        if (!Enqueue(b)) return;
        if (done && b->n > 0) {
          /* a short final batch still needs a 0-record epoch marker so
           * the consumer's next call sees the end */
          Batch *mark = new Batch();
          mark->epoch_end = true;
          if (!Enqueue(mark)) return;
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        finished_ = true;
      }
      cv_nonempty_.notify_all();
    } catch (const std::exception &e) {
      std::lock_guard<std::mutex> lk(mu_);
      error_ = e.what();
      error_set_ = true;
      cv_nonempty_.notify_all();
    }
  }

  std::string path_;
  int batch_;
  int capacity_;
  std::vector<uint64_t> index_;

  std::thread producer_;
  std::mutex mu_;
  std::condition_variable cv_nonempty_;
  std::condition_variable cv_nonfull_;
  std::deque<Batch *> queue_;
  Batch *last_ = nullptr;
  bool stop_ = false;
  bool finished_ = false;
  bool error_set_ = false;
  std::string error_;
};

}  // namespace
}  // namespace mxtpu

#define API_BEGIN() try {
#define API_END()                        \
  }                                      \
  catch (const std::exception &e) {      \
    mxtpu::SetLastError(e.what());       \
    return -1;                           \
  }                                      \
  return 0;

extern "C" {

int MXRecordIOWriterCreate(const char *path, RecordIOHandle *out) {
  API_BEGIN();
  *out = new mxtpu::Writer(path);
  API_END();
}

int MXRecordIOWriterWrite(RecordIOHandle h, const char *data, uint64_t size,
                          uint64_t *out_pos) {
  API_BEGIN();
  uint64_t pos = static_cast<mxtpu::Writer *>(h)->Write(data, size);
  if (out_pos) *out_pos = pos;
  API_END();
}

int MXRecordIOWriterTell(RecordIOHandle h, uint64_t *out_pos) {
  API_BEGIN();
  *out_pos = static_cast<mxtpu::Writer *>(h)->Tell();
  API_END();
}

int MXRecordIOWriterFree(RecordIOHandle h) {
  API_BEGIN();
  delete static_cast<mxtpu::Writer *>(h);
  API_END();
}

int MXRecordIOReaderCreate(const char *path, RecordIOHandle *out) {
  API_BEGIN();
  *out = new mxtpu::Reader(path);
  API_END();
}

int MXRecordIOReaderNext(RecordIOHandle h, const char **out_data,
                         uint64_t *out_size) {
  API_BEGIN();
  uint64_t size = 0;
  const char *data = static_cast<mxtpu::Reader *>(h)->Next(&size);
  *out_data = data;
  *out_size = data ? size : 0;
  API_END();
}

int MXRecordIOReaderSeek(RecordIOHandle h, uint64_t pos) {
  API_BEGIN();
  static_cast<mxtpu::Reader *>(h)->Seek(pos);
  API_END();
}

int MXRecordIOReaderTell(RecordIOHandle h, uint64_t *out_pos) {
  API_BEGIN();
  *out_pos = static_cast<mxtpu::Reader *>(h)->Tell();
  API_END();
}

int MXRecordIOReaderScanIndex(RecordIOHandle h, uint64_t **out_positions,
                              uint64_t *out_count) {
  API_BEGIN();
  std::vector<uint64_t> pos = static_cast<mxtpu::Reader *>(h)->ScanIndex();
  uint64_t *buf = static_cast<uint64_t *>(
      std::malloc(sizeof(uint64_t) * (pos.empty() ? 1 : pos.size())));
  std::memcpy(buf, pos.data(), sizeof(uint64_t) * pos.size());
  *out_positions = buf;
  *out_count = pos.size();
  API_END();
}

int MXRecordIOReaderFree(RecordIOHandle h) {
  API_BEGIN();
  delete static_cast<mxtpu::Reader *>(h);
  API_END();
}

int MXFreeBuffer(void *buf) {
  std::free(buf);
  return 0;
}

int MXPrefetcherCreate(const char *path, int batch_size, int capacity,
                       const uint64_t *index, uint64_t index_len,
                       PrefetcherHandle *out) {
  API_BEGIN();
  *out = new mxtpu::Prefetcher(path, batch_size, capacity, index, index_len);
  API_END();
}

int MXPrefetcherNext(PrefetcherHandle h, const char **data, uint64_t *sizes,
                     int *out_n) {
  API_BEGIN();
  mxtpu::Prefetcher::Batch *b =
      static_cast<mxtpu::Prefetcher *>(h)->Next();
  for (int i = 0; i < b->n; ++i) {
    data[i] = b->data + b->offsets[i];
    sizes[i] = b->sizes[i];
  }
  *out_n = b->n;
  API_END();
}

int MXPrefetcherReset(PrefetcherHandle h) {
  API_BEGIN();
  static_cast<mxtpu::Prefetcher *>(h)->Reset();
  API_END();
}

int MXPrefetcherFree(PrefetcherHandle h) {
  API_BEGIN();
  delete static_cast<mxtpu::Prefetcher *>(h);
  API_END();
}

}  // extern "C"
