/*
 * json.h — minimal JSON value + recursive-descent parser shared by the
 * native deployment surfaces (predict.cc, symbol.cc). Covers exactly the
 * schema HybridBlock.export() emits (objects / arrays / strings / numbers
 * / bools / null, ASCII \u escapes); not a general-purpose JSON library.
 * Reference parity: the role nlohmann/dmlc json played for
 * src/c_api_symbolic.cc and src/c_predict_api.cc.
 */
#ifndef MXTPU_JSON_H_
#define MXTPU_JSON_H_

#include <cctype>
#include <cstring>
#include <map>
#include <stdexcept>
#include <string>
#include <vector>

namespace mxtpu {

struct JValue {
  enum Kind { NUL, BOOL, NUM, STR, ARR, OBJ } kind = NUL;
  bool b = false;
  double num = 0.0;
  std::string str;
  std::vector<JValue> arr;
  std::map<std::string, JValue> obj;

  const JValue *get(const std::string &k) const {
    auto it = obj.find(k);
    return it == obj.end() ? nullptr : &it->second;
  }
};

struct JParser {
  const char *p, *end;
  explicit JParser(const std::string &s)
      : p(s.data()), end(s.data() + s.size()) {}

  [[noreturn]] void fail(const char *msg) {
    throw std::runtime_error(std::string("json parse error: ") + msg);
  }
  void ws() {
    while (p < end && std::isspace(static_cast<unsigned char>(*p))) ++p;
  }
  char peek() {
    ws();
    if (p >= end) fail("unexpected end");
    return *p;
  }
  void expect(char c) {
    if (peek() != c) fail("unexpected character");
    ++p;
  }
  JValue parse() {
    JValue v = value();
    ws();
    return v;
  }
  JValue value() {
    switch (peek()) {
      case '{': return object();
      case '[': return array();
      case '"': { JValue v; v.kind = JValue::STR; v.str = string(); return v; }
      case 't': lit("true");  { JValue v; v.kind = JValue::BOOL; v.b = true;  return v; }
      case 'f': lit("false"); { JValue v; v.kind = JValue::BOOL; v.b = false; return v; }
      case 'n': lit("null");  return JValue();
      default:  return number();
    }
  }
  void lit(const char *s) {
    ws();
    size_t n = std::strlen(s);
    if (p + n > end || std::strncmp(p, s, n) != 0) fail("bad literal");
    p += n;
  }
  JValue number() {
    ws();
    char *q = nullptr;
    JValue v;
    v.kind = JValue::NUM;
    v.num = std::strtod(p, &q);
    if (q == p) fail("bad number");
    p = q;
    return v;
  }
  std::string string() {
    expect('"');
    std::string s;
    while (p < end && *p != '"') {
      if (*p == '\\') {
        ++p;
        if (p >= end) fail("bad escape");
        switch (*p) {
          case 'n': s += '\n'; break;
          case 't': s += '\t'; break;
          case 'r': s += '\r'; break;
          case 'b': s += '\b'; break;
          case 'f': s += '\f'; break;
          case 'u': {             /* ASCII subset only */
            if (p + 4 >= end) fail("bad \\u");
            s += static_cast<char>(
                std::strtol(std::string(p + 1, 4).c_str(), nullptr, 16));
            p += 4;
            break;
          }
          default: s += *p;
        }
        ++p;
      } else {
        s += *p++;
      }
    }
    if (p >= end) fail("unterminated string");
    ++p;
    return s;
  }
  JValue array() {
    expect('[');
    JValue v;
    v.kind = JValue::ARR;
    if (peek() == ']') { ++p; return v; }
    for (;;) {
      v.arr.push_back(value());
      char c = peek();
      if (c == ',') { ++p; continue; }
      if (c == ']') { ++p; break; }
      fail("expected , or ]");
    }
    return v;
  }
  JValue object() {
    expect('{');
    JValue v;
    v.kind = JValue::OBJ;
    if (peek() == '}') { ++p; return v; }
    for (;;) {
      std::string k = string();
      expect(':');
      v.obj[k] = value();
      char c = peek();
      if (c == ',') { ++p; continue; }
      if (c == '}') { ++p; break; }
      fail("expected , or }");
    }
    return v;
  }
};

}  // namespace mxtpu

#endif  // MXTPU_JSON_H_
