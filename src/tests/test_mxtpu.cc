/*
 * Native runtime unit tests.
 *
 * Reference parity (leezu/mxnet): tests/cpp/engine/threaded_engine_test.cc
 * (random dependency DAGs stressing the engine, compared against serial
 * execution), tests/cpp/storage/storage_test.cc, and recordio framing
 * round-trips.  Assert-based single binary (`make -C src test`).
 */
#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <fstream>
#include <random>
#include <string>
#include <vector>

#include "../mxtpu.h"

#define CHECK(cond)                                                        \
  do {                                                                     \
    if (!(cond)) {                                                         \
      std::fprintf(stderr, "CHECK failed at %s:%d: %s\n", __FILE__,        \
                   __LINE__, #cond);                                       \
      std::fprintf(stderr, "last error: %s\n", MXGetLastError());          \
      std::exit(1);                                                        \
    }                                                                      \
  } while (0)

/* ---- engine: random DAG result must equal serial execution ---- */

struct DagCtx {
  std::vector<long long> *cells;
  std::vector<int> reads;
  std::vector<int> writes;
  int serial;     /* op id, for the serial replay */
};

static void dag_fn(void *vctx) {
  DagCtx *c = static_cast<DagCtx *>(vctx);
  long long acc = 1;
  for (int r : c->reads) acc += (*c->cells)[r];
  for (int w : c->writes) (*c->cells)[w] = (*c->cells)[w] * 31 + acc;
}

static std::vector<long long> run_dag(int n_vars, int n_ops, int naive,
                                      unsigned seed) {
  EngineHandle eng;
  CHECK(MXEngineCreate(4, naive, &eng) == 0);
  std::vector<EngineVarHandle> vars(n_vars);
  for (int i = 0; i < n_vars; ++i)
    CHECK(MXEngineNewVar(eng, &vars[i]) == 0);

  std::vector<long long> cells(n_vars, 0);
  std::mt19937 rng(seed);
  std::vector<DagCtx> ctxs(n_ops);
  for (int op = 0; op < n_ops; ++op) {
    DagCtx &c = ctxs[op];
    c.cells = &cells;
    c.serial = op;
    int n_read = 1 + (int)(rng() % 3), n_write = 1 + (int)(rng() % 2);
    for (int i = 0; i < n_read; ++i) c.reads.push_back(rng() % n_vars);
    for (int i = 0; i < n_write; ++i) c.writes.push_back(rng() % n_vars);
    std::vector<EngineVarHandle> rv, wv;
    for (int r : c.reads) rv.push_back(vars[r]);
    for (int w : c.writes) wv.push_back(vars[w]);
    CHECK(MXEnginePushAsync(eng, dag_fn, &c, nullptr, rv.data(),
                            (int)rv.size(), wv.data(), (int)wv.size(), 0,
                            "dag_op") == 0);
  }
  CHECK(MXEngineWaitAll(eng) == 0);
  for (int i = 0; i < n_vars; ++i) CHECK(MXEngineFreeVar(eng, vars[i]) == 0);
  CHECK(MXEngineFree(eng) == 0);
  return cells;
}

static void test_engine_dag_matches_serial() {
  for (unsigned seed = 1; seed <= 5; ++seed) {
    std::vector<long long> threaded = run_dag(8, 200, /*naive=*/0, seed);
    std::vector<long long> serial = run_dag(8, 200, /*naive=*/1, seed);
    CHECK(threaded == serial);
  }
  std::puts("engine_dag_matches_serial OK");
}

/* writers to one var must serialize: counter increments never lost */
struct IncCtx { std::atomic<int> *started; long long *cell; };
static void inc_fn(void *vctx) {
  IncCtx *c = static_cast<IncCtx *>(vctx);
  c->started->fetch_add(1);
  long long v = *c->cell;            /* deliberate read-modify-write */
  for (volatile int i = 0; i < 100; ++i) {}
  *c->cell = v + 1;
}

static void test_engine_writer_serialization() {
  EngineHandle eng;
  CHECK(MXEngineCreate(8, 0, &eng) == 0);
  EngineVarHandle var;
  CHECK(MXEngineNewVar(eng, &var) == 0);
  std::atomic<int> started{0};
  long long cell = 0;
  const int kOps = 500;
  std::vector<IncCtx> ctxs(kOps, IncCtx{&started, &cell});
  for (int i = 0; i < kOps; ++i)
    CHECK(MXEnginePushAsync(eng, inc_fn, &ctxs[i], nullptr, nullptr, 0,
                            &var, 1, 0, "inc") == 0);
  CHECK(MXEngineWaitForVar(eng, var) == 0);
  CHECK(cell == kOps);
  CHECK(started.load() == kOps);
  CHECK(MXEngineFreeVar(eng, var) == 0);
  CHECK(MXEngineFree(eng) == 0);
  std::puts("engine_writer_serialization OK");
}

static void test_engine_profile_dump() {
  EngineHandle eng;
  CHECK(MXEngineCreate(2, 0, &eng) == 0);
  CHECK(MXEngineSetProfiling(eng, 1) == 0);
  EngineVarHandle var;
  CHECK(MXEngineNewVar(eng, &var) == 0);
  std::atomic<int> started{0};
  long long cell = 0;
  IncCtx c{&started, &cell};
  CHECK(MXEnginePushAsync(eng, inc_fn, &c, nullptr, nullptr, 0, &var, 1, 0,
                          "profiled_op") == 0);
  CHECK(MXEngineWaitAll(eng) == 0);
  char *json = nullptr;
  CHECK(MXEngineDumpProfile(eng, &json) == 0);
  CHECK(json != nullptr);
  CHECK(std::strstr(json, "profiled_op") != nullptr);
  CHECK(std::strstr(json, "\"ph\"") != nullptr);
  CHECK(MXFreeString(json) == 0);
  CHECK(MXEngineFreeVar(eng, var) == 0);
  CHECK(MXEngineFree(eng) == 0);
  std::puts("engine_profile_dump OK");
}

/* ---- storage pool ---- */

static void test_storage_pool_reuse() {
  CHECK(MXStorageReleaseAll() == 0);
  void *a = nullptr;
  CHECK(MXStorageAlloc(1 << 20, &a) == 0 && a != nullptr);
  std::memset(a, 0xAB, 1 << 20);
  CHECK(MXStorageFree(a) == 0);
  void *b = nullptr;
  CHECK(MXStorageAlloc(1 << 20, &b) == 0);
  uint64_t in_use, pooled, hits, misses;
  CHECK(MXStorageStats(&in_use, &pooled, &hits, &misses) == 0);
  CHECK(hits >= 1);          /* second alloc served from the pool */
  CHECK(in_use >= (1 << 20));
  CHECK(MXStorageFree(b) == 0);
  CHECK(MXStorageReleaseAll() == 0);
  std::puts("storage_pool_reuse OK");
}

/* ---- recordio ---- */

static void test_recordio_roundtrip() {
  const char *path = "/tmp/mxtpu_test.rec";
  RecordIOHandle w;
  CHECK(MXRecordIOWriterCreate(path, &w) == 0);
  std::vector<std::string> recs;
  std::mt19937 rng(7);
  for (int i = 0; i < 50; ++i) {
    std::string s(1 + rng() % 300, '\0');
    for (auto &ch : s) ch = (char)(rng() & 0xFF);   /* incl. magic bytes */
    uint64_t pos;
    CHECK(MXRecordIOWriterWrite(w, s.data(), s.size(), &pos) == 0);
    recs.push_back(s);
  }
  CHECK(MXRecordIOWriterFree(w) == 0);

  RecordIOHandle r;
  CHECK(MXRecordIOReaderCreate(path, &r) == 0);
  uint64_t *positions; uint64_t count;
  CHECK(MXRecordIOReaderScanIndex(r, &positions, &count) == 0);
  CHECK(count == recs.size());
  for (size_t i = 0; i < recs.size(); ++i) {
    const char *data; uint64_t size;
    CHECK(MXRecordIOReaderNext(r, &data, &size) == 0);
    CHECK(data != nullptr && size == recs[i].size());
    CHECK(std::memcmp(data, recs[i].data(), size) == 0);
  }
  const char *data; uint64_t size;
  CHECK(MXRecordIOReaderNext(r, &data, &size) == 0);
  CHECK(data == nullptr);       /* EOF */
  /* random access via the index */
  CHECK(MXRecordIOReaderSeek(r, positions[10]) == 0);
  CHECK(MXRecordIOReaderNext(r, &data, &size) == 0);
  CHECK(size == recs[10].size());
  CHECK(std::memcmp(data, recs[10].data(), size) == 0);
  CHECK(MXFreeBuffer(positions) == 0);
  CHECK(MXRecordIOReaderFree(r) == 0);
  std::remove(path);
  std::puts("recordio_roundtrip OK");
}

static void test_error_message() {
  RecordIOHandle r;
  CHECK(MXRecordIOReaderCreate("/nonexistent/path.rec", &r) != 0);
  CHECK(std::strlen(MXGetLastError()) > 0);
  std::puts("error_message OK");
}

/* ---- NDArray C surface (c_api_ndarray.cc analog) ---- */

static void test_ndarray_create_invoke() {
  int64_t shape[2] = {2, 3};
  NDArrayHandle a, b, c, d;
  CHECK(MXNDArrayCreate(shape, 2, 0, &a) == 0);
  CHECK(MXNDArrayCreate(shape, 2, 0, &b) == 0);
  CHECK(MXNDArrayCreate(shape, 2, 0, &c) == 0);
  float av[6] = {1, 2, 3, 4, 5, 6};
  float bv[6] = {10, 20, 30, 40, 50, 60};
  CHECK(MXNDArraySyncCopyFromCPU(a, av, sizeof(av)) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(b, bv, sizeof(bv)) == 0);
  /* chained async ops through the engine: c = a + b; c = c * a */
  NDArrayHandle in1[2] = {a, b};
  CHECK(MXImperativeInvoke("add", in1, 2, &c, 1) == 0);
  NDArrayHandle in2[2] = {c, a};
  CHECK(MXNDArrayCreate(shape, 2, 0, &d) == 0);
  CHECK(MXImperativeInvoke("mul", in2, 2, &d, 1) == 0);
  float out[6];
  CHECK(MXNDArraySyncCopyToCPU(d, out, sizeof(out)) == 0);
  for (int i = 0; i < 6; ++i)
    CHECK(out[i] == (av[i] + bv[i]) * av[i]);
  /* dot: (2,3)x(3,2) */
  int64_t sb[2] = {3, 2}, sc[2] = {2, 2};
  NDArrayHandle m, r;
  CHECK(MXNDArrayCreate(sb, 2, 0, &m) == 0);
  float mv[6] = {1, 0, 0, 1, 1, 1};
  CHECK(MXNDArraySyncCopyFromCPU(m, mv, sizeof(mv)) == 0);
  CHECK(MXNDArrayCreate(sc, 2, 0, &r) == 0);
  NDArrayHandle in3[2] = {a, m};
  CHECK(MXImperativeInvoke("dot", in3, 2, &r, 1) == 0);
  float rv[4];
  CHECK(MXNDArraySyncCopyToCPU(r, rv, sizeof(rv)) == 0);
  /* [[1,2,3],[4,5,6]] @ [[1,0],[0,1],[1,1]] = [[4,5],[10,11]] */
  CHECK(rv[0] == 4 && rv[1] == 5 && rv[2] == 10 && rv[3] == 11);
  /* arity + unknown-op errors surface through MXGetLastError */
  CHECK(MXImperativeInvoke("nonsense_op", in1, 2, &c, 1) != 0);
  CHECK(std::strlen(MXGetLastError()) > 0);
  int n_ops = 0;
  const char **names;
  CHECK(MXListAllOpNames(&n_ops, &names) == 0);
  CHECK(n_ops >= 10);
  for (NDArrayHandle h : {a, b, c, d, m, r}) CHECK(MXNDArrayFree(h) == 0);
  std::puts("ndarray_create_invoke OK");
}

static void test_ndarray_params_roundtrip() {
  const char *path = "/tmp/mxtpu_capi_test.params";
  int64_t s1[2] = {2, 2};
  int64_t s2[1] = {3};
  NDArrayHandle a, b;
  CHECK(MXNDArrayCreate(s1, 2, 0, &a) == 0);
  CHECK(MXNDArrayCreate(s2, 1, 4, &b) == 0);
  float av[4] = {1.5f, -2.5f, 3.0f, 0.25f};
  int32_t bv[3] = {7, -8, 9};
  CHECK(MXNDArraySyncCopyFromCPU(a, av, sizeof(av)) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(b, bv, sizeof(bv)) == 0);
  NDArrayHandle hs[2] = {a, b};
  const char *nm[2] = {"weight", "steps"};
  CHECK(MXNDArraySave(path, 2, hs, nm) == 0);
  int n = 0;
  NDArrayHandle *lh;
  char **ln;
  CHECK(MXNDArrayLoad(path, &n, &lh, &ln) == 0);
  CHECK(n == 2);
  CHECK(std::strcmp(ln[0], "weight") == 0);
  CHECK(std::strcmp(ln[1], "steps") == 0);
  int nd;
  const int64_t *sh;
  CHECK(MXNDArrayGetShape(lh[0], &nd, &sh) == 0);
  CHECK(nd == 2 && sh[0] == 2 && sh[1] == 2);
  int dt;
  CHECK(MXNDArrayGetDType(lh[1], &dt) == 0);
  CHECK(dt == 4);
  float ra[4];
  CHECK(MXNDArraySyncCopyToCPU(lh[0], ra, sizeof(ra)) == 0);
  for (int i = 0; i < 4; ++i) CHECK(ra[i] == av[i]);
  int32_t rb[3];
  CHECK(MXNDArraySyncCopyToCPU(lh[1], rb, sizeof(rb)) == 0);
  for (int i = 0; i < 3; ++i) CHECK(rb[i] == bv[i]);
  for (int i = 0; i < n; ++i) CHECK(MXNDArrayFree(lh[i]) == 0);
  CHECK(MXNDArrayLoadFree(n, lh, ln) == 0);
  CHECK(MXNDArrayFree(a) == 0);
  CHECK(MXNDArrayFree(b) == 0);
  std::remove(path);
  std::puts("ndarray_params_roundtrip OK");
}

static void test_predict_mlp() {
  /* c_predict_api analog, fully C-side: write a deploy json + params
   * with the C API, then classify through MXPredCreate/Forward. The
   * 2-layer net computes relu(x W1^T + b1) W2^T + b2 with hand-picked
   * weights so the expected logits are known exactly. */
  const char *pp = "/tmp/mxtpu_pred_test.params";
  const char *sp = "/tmp/mxtpu_pred_test-symbol.json";
  {
    std::ofstream f(sp);
    f << "{\n  \"deploy_graph\": [\n"
         "    {\"op\": \"dense\", \"weight\": \"l1.weight\", "
         "\"bias\": \"l1.bias\", \"flatten\": 1, "
         "\"activation\": \"relu\"},\n"
         "    {\"op\": \"dense\", \"weight\": \"l2.weight\", "
         "\"bias\": null, \"flatten\": 0, \"activation\": null},\n"
         "    {\"op\": \"softmax\"}\n  ]\n}\n";
  }
  /* l1: 3 units over 2 inputs; l2: 2 units over 3 */
  float w1[6] = {1, 0, 0, 1, 1, -1};
  float b1[3] = {0, 0, 0.5f};
  float w2[6] = {1, 0, 1, 0, 1, -1};
  int64_t s_w1[2] = {3, 2}, s_b1[1] = {3}, s_w2[2] = {2, 3};
  NDArrayHandle hw1, hb1, hw2;
  CHECK(MXNDArrayCreate(s_w1, 2, 0, &hw1) == 0);
  CHECK(MXNDArrayCreate(s_b1, 1, 0, &hb1) == 0);
  CHECK(MXNDArrayCreate(s_w2, 2, 0, &hw2) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(hw1, w1, sizeof(w1)) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(hb1, b1, sizeof(b1)) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(hw2, w2, sizeof(w2)) == 0);
  NDArrayHandle hs[3] = {hw1, hb1, hw2};
  const char *nm[3] = {"l1.weight", "l1.bias", "l2.weight"};
  CHECK(MXNDArraySave(pp, 3, hs, nm) == 0);

  PredictorHandle pred;
  int64_t in_shape[2] = {1, 2};
  CHECK(MXPredCreate(sp, pp, in_shape, 2, &pred) == 0);
  float x[2] = {2.0f, 1.0f};
  CHECK(MXPredSetInput(pred, x, 2) == 0);
  CHECK(MXPredForward(pred) == 0);
  int nd;
  const int64_t *osh;
  CHECK(MXPredGetOutputShape(pred, &nd, &osh) == 0);
  CHECK(nd == 2 && osh[0] == 1 && osh[1] == 2);
  float out[2];
  CHECK(MXPredGetOutput(pred, out, 2) == 0);
  /* h = relu([2, 1, 2+(-1)+0.5]) = [2, 1, 1.5];
   * logits = [2+1.5, 1-1.5] = [3.5, -0.5]; softmax(3.5, -0.5) */
  float e0 = std::exp(3.5f), e1 = std::exp(-0.5f);
  CHECK(std::fabs(out[0] - e0 / (e0 + e1)) < 1e-5f);
  CHECK(std::fabs(out[1] - e1 / (e0 + e1)) < 1e-5f);
  CHECK(out[0] > out[1]);                   /* class 0 wins */
  /* a second forward reuses the graph */
  CHECK(MXPredForward(pred) == 0);
  CHECK(MXPredFree(pred) == 0);
  CHECK(MXNDArrayFree(hw1) == 0);
  CHECK(MXNDArrayFree(hb1) == 0);
  CHECK(MXNDArrayFree(hw2) == 0);
  std::remove(pp);
  std::remove(sp);
  std::puts("predict_mlp OK");
}

static void test_symbol_api() {
  /* c_api_symbolic analog: parse an export meta, list arguments vs
   * auxiliary states, outputs, deploy ops, attrs, input shapes; then
   * build a predictor from the symbol handle and verify it matches the
   * known MLP numbers from test_predict_mlp. */
  const char *json =
      "{\n"
      "  \"framework\": \"mxnet_tpu\",\n"
      "  \"format_version\": 1,\n"
      "  \"block\": \"TestNet\",\n"
      "  \"inputs\": [{\"shape\": [1, 2], \"dtype\": \"float32\"}],\n"
      "  \"param_order\": [\"l1.weight\", \"l1.bias\", \"l2.weight\",\n"
      "                    \"bn.running_mean\", \"bn.running_var\"],\n"
      "  \"deploy_graph\": [\n"
      "    {\"op\": \"dense\", \"weight\": \"l1.weight\", "
      "\"bias\": \"l1.bias\", \"flatten\": 1, \"activation\": \"relu\"},\n"
      "    {\"op\": \"dense\", \"weight\": \"l2.weight\", "
      "\"bias\": null, \"flatten\": 0, \"activation\": null},\n"
      "    {\"op\": \"softmax\"}\n  ]\n}\n";

  SymbolHandle sym;
  CHECK(MXSymbolCreateFromJSON(json, &sym) == 0);

  int n = 0;
  const char **names = nullptr;
  CHECK(MXSymbolListArguments(sym, &n, &names) == 0);
  CHECK(n == 3);
  CHECK(std::strcmp(names[0], "l1.weight") == 0);
  CHECK(std::strcmp(names[1], "l1.bias") == 0);
  CHECK(std::strcmp(names[2], "l2.weight") == 0);
  CHECK(MXSymbolListAuxiliaryStates(sym, &n, &names) == 0);
  CHECK(n == 2);
  CHECK(std::strcmp(names[0], "bn.running_mean") == 0);
  CHECK(std::strcmp(names[1], "bn.running_var") == 0);
  CHECK(MXSymbolListOutputs(sym, &n, &names) == 0);
  CHECK(n == 1);
  CHECK(std::strcmp(names[0], "testnet_output") == 0);
  CHECK(MXSymbolListDeployOps(sym, &n, &names) == 0);
  CHECK(n == 3);
  CHECK(std::strcmp(names[0], "dense") == 0);
  CHECK(std::strcmp(names[2], "softmax") == 0);

  const char *attr = nullptr;
  CHECK(MXSymbolGetAttr(sym, "framework", &attr) == 0);
  CHECK(attr != nullptr && std::strcmp(attr, "mxnet_tpu") == 0);
  CHECK(MXSymbolGetAttr(sym, "format_version", &attr) == 0);
  CHECK(attr != nullptr && std::strcmp(attr, "1") == 0);
  CHECK(MXSymbolGetAttr(sym, "no_such_key", &attr) == 0);
  CHECK(attr == nullptr);

  CHECK(MXSymbolGetNumInputs(sym, &n) == 0);
  CHECK(n == 1);
  int nd = 0;
  const int64_t *shape = nullptr;
  const char *dtype = nullptr;
  CHECK(MXSymbolGetInputShape(sym, 0, &nd, &shape, &dtype) == 0);
  CHECK(nd == 2 && shape[0] == 1 && shape[1] == 2);
  CHECK(std::strcmp(dtype, "float32") == 0);
  CHECK(MXSymbolGetInputShape(sym, 1, &nd, &shape, &dtype) != 0);

  /* json round-trip: save, re-create, same argument list */
  char *text = nullptr;
  CHECK(MXSymbolSaveToJSON(sym, &text) == 0);
  SymbolHandle sym2;
  CHECK(MXSymbolCreateFromJSON(text, &sym2) == 0);
  CHECK(MXFreeString(text) == 0);
  CHECK(MXSymbolListArguments(sym2, &n, &names) == 0);
  CHECK(n == 3 && std::strcmp(names[2], "l2.weight") == 0);
  CHECK(MXSymbolFree(sym2) == 0);

  /* predictor from symbol: same weights as test_predict_mlp (the aux
   * names in param_order are absent from the graph, so the .params file
   * does not need them) */
  const char *pp = "/tmp/mxtpu_sym_test.params";
  float w1[6] = {1, 0, 0, 1, 1, -1};
  float b1[3] = {0, 0, 0.5f};
  float w2[6] = {1, 0, 1, 0, 1, -1};
  int64_t s_w1[2] = {3, 2}, s_b1[1] = {3}, s_w2[2] = {2, 3};
  NDArrayHandle hw1, hb1, hw2;
  CHECK(MXNDArrayCreate(s_w1, 2, 0, &hw1) == 0);
  CHECK(MXNDArrayCreate(s_b1, 1, 0, &hb1) == 0);
  CHECK(MXNDArrayCreate(s_w2, 2, 0, &hw2) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(hw1, w1, sizeof(w1)) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(hb1, b1, sizeof(b1)) == 0);
  CHECK(MXNDArraySyncCopyFromCPU(hw2, w2, sizeof(w2)) == 0);
  NDArrayHandle hs[3] = {hw1, hb1, hw2};
  const char *nm[3] = {"l1.weight", "l1.bias", "l2.weight"};
  CHECK(MXNDArraySave(pp, 3, hs, nm) == 0);

  PredictorHandle pred;
  int64_t in_shape[2] = {1, 2};
  CHECK(MXPredCreateFromSymbol(sym, pp, in_shape, 2, &pred) == 0);
  float x[2] = {2.0f, 1.0f};
  CHECK(MXPredSetInput(pred, x, 2) == 0);
  CHECK(MXPredForward(pred) == 0);
  float out[2];
  CHECK(MXPredGetOutput(pred, out, 2) == 0);
  float e0 = std::exp(3.5f), e1 = std::exp(-0.5f);
  CHECK(std::fabs(out[0] - e0 / (e0 + e1)) < 1e-5f);
  CHECK(MXPredFree(pred) == 0);
  CHECK(MXSymbolFree(sym) == 0);
  CHECK(MXNDArrayFree(hw1) == 0);
  CHECK(MXNDArrayFree(hb1) == 0);
  CHECK(MXNDArrayFree(hw2) == 0);
  std::remove(pp);
  std::puts("symbol_api OK");
}

int main() {
  test_engine_dag_matches_serial();
  test_engine_writer_serialization();
  test_engine_profile_dump();
  test_storage_pool_reuse();
  test_recordio_roundtrip();
  test_error_message();
  test_ndarray_create_invoke();
  test_ndarray_params_roundtrip();
  test_predict_mlp();
  test_symbol_api();
  std::puts("ALL C++ TESTS PASSED");
  return 0;
}
