/*
 * capi_common.h — shared plumbing for the C API translation units:
 * the error trampoline macros (every extern "C" entry funnels exceptions
 * into MXGetLastError, reference API_BEGIN/API_END in c_api_common.h)
 * and small file helpers used by the deployment surfaces.
 */
#ifndef MXTPU_CAPI_COMMON_H_
#define MXTPU_CAPI_COMMON_H_

#include <fstream>
#include <sstream>
#include <stdexcept>
#include <string>

namespace mxtpu {

void SetLastError(const std::string &msg);  /* c_api.cc */

inline std::string ReadFile(const char *path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) throw std::runtime_error(std::string("cannot open ") + path);
  std::ostringstream ss;
  ss << f.rdbuf();
  return ss.str();
}

}  // namespace mxtpu

#define API_BEGIN() try {
#define API_END()                             \
  }                                           \
  catch (const std::exception &e) {           \
    ::mxtpu::SetLastError(e.what());          \
    return -1;                                \
  }                                           \
  catch (...) {                               \
    ::mxtpu::SetLastError("unknown C++ error"); \
    return -1;                                \
  }                                           \
  return 0;

#endif  // MXTPU_CAPI_COMMON_H_
