/*
 * engine.cc — asynchronous dependency engine.
 *
 * Reference parity (leezu/mxnet): src/engine/threaded_engine.{h,cc},
 * src/engine/threaded_engine_perdevice.cc, src/engine/naive_engine.cc.
 *
 * The scheduling model is the reference's: an op is pushed with lists of
 * read and write vars; each var serialises writers and parallelises
 * readers in FIFO order (ThreadedVar); when every var has granted access
 * the op is dispatched to a worker pool; on completion each var releases
 * its grant and wakes successors.  Unlike the reference there is no
 * device-stream dimension — XLA owns device ordering — so this engine
 * schedules *host* work: IO decode, custom Python ops, checkpoint writes.
 */
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <string>
#include <thread>
#include <vector>

#include "./mxtpu.h"

namespace mxtpu {

void SetLastError(const std::string &msg);

namespace {

/* Minimal JSON string escape for chrome-trace op names. */
std::string JsonEscape(const std::string &s) {
  std::string out;
  out.reserve(s.size());
  for (char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\t': out += "\\t"; break;
      case '\r': out += "\\r"; break;
      default:
        if (static_cast<unsigned char>(c) < 0x20) {
          char buf[8];
          std::snprintf(buf, sizeof(buf), "\\u%04x", c);
          out += buf;
        } else {
          out += c;
        }
    }
  }
  return out;
}

struct Opr;

/* ThreadedVar analog: FIFO queue of pending accesses per var. */
struct Var {
  std::mutex mu;
  struct Pending {
    Opr *op;
    bool write;
  };
  std::deque<Pending> queue;
  int active_reads = 0;
  bool active_write = false;
  bool to_delete = false;  /* free requested; delete when drained */

  /* Called with mu held.  Grants queued accesses that can proceed now;
   * returns ops whose dependency count hit zero. */
  void Grant(std::vector<Opr *> *ready);
};

struct Opr {
  MXEngineFn fn;
  void *ctx;
  MXEngineOnComplete on_complete;
  std::vector<Var *> reads;
  std::vector<Var *> writes;
  std::atomic<int> wait_count{0};
  int priority = 0;
  std::string name;
};

struct ProfileEvent {
  std::string name;
  uint64_t tid;
  uint64_t start_us;
  uint64_t dur_us;
};

class Engine {
 public:
  Engine(int num_workers, bool naive) : naive_(naive) {
    if (!naive_) {
      if (num_workers <= 0) {
        num_workers = static_cast<int>(std::thread::hardware_concurrency());
        if (num_workers <= 0) num_workers = 4;
      }
      for (int i = 0; i < num_workers; ++i) {
        workers_.emplace_back([this] { WorkerLoop(); });
      }
    }
  }

  ~Engine() {
    WaitAll();
    {
      std::lock_guard<std::mutex> lk(mu_);
      shutdown_ = true;
    }
    cv_.notify_all();
    for (auto &t : workers_) t.join();
  }

  Var *NewVar() { return new Var(); }

  void FreeVar(Var *v) {
    bool idle;
    {
      std::lock_guard<std::mutex> lk(v->mu);
      v->to_delete = true;
      idle = v->queue.empty() && v->active_reads == 0 && !v->active_write;
    }
    if (idle) delete v;
  }

  void Push(MXEngineFn fn, void *ctx, MXEngineOnComplete on_complete,
            EngineVarHandle *read_vars, int n_read,
            EngineVarHandle *write_vars, int n_write, int priority,
            const char *name) {
    Opr *op = new Opr();
    op->fn = fn;
    op->ctx = ctx;
    op->on_complete = on_complete;
    op->priority = priority;
    if (name) op->name = name;
    /* Dedup: a var in both lists is write-only (reference dedups
     * const_vars against mutable_vars in Engine::PushAsync). */
    for (int i = 0; i < n_write; ++i) {
      Var *v = static_cast<Var *>(write_vars[i]);
      bool seen = false;
      for (Var *w : op->writes) seen = seen || (w == v);
      if (!seen) op->writes.push_back(v);
    }
    for (int i = 0; i < n_read; ++i) {
      Var *v = static_cast<Var *>(read_vars[i]);
      bool seen = false;
      for (Var *w : op->writes) seen = seen || (w == v);
      for (Var *w : op->reads) seen = seen || (w == v);
      if (!seen) op->reads.push_back(v);
    }
    pending_.fetch_add(1, std::memory_order_relaxed);

    int ndeps = static_cast<int>(op->reads.size() + op->writes.size());
    if (ndeps == 0) {
      Dispatch(op);
      return;
    }
    op->wait_count.store(ndeps, std::memory_order_relaxed);
    std::vector<Opr *> ready;
    for (Var *v : op->reads) {
      std::lock_guard<std::mutex> lk(v->mu);
      v->queue.push_back({op, false});
      v->Grant(&ready);
    }
    for (Var *v : op->writes) {
      std::lock_guard<std::mutex> lk(v->mu);
      v->queue.push_back({op, true});
      v->Grant(&ready);
    }
    for (Opr *r : ready) Dispatch(r);
  }

  void WaitForVar(Var *v) {
    /* Push a no-op write on the var and wait for it (WaitForVar in
     * threaded_engine.cc uses the same trick). */
    struct Sync {
      std::mutex mu;
      std::condition_variable cv;
      bool done = false;
    } sync;
    EngineVarHandle wv = v;
    Push(
        [](void *c) {
          Sync *s = static_cast<Sync *>(c);
          std::lock_guard<std::mutex> lk(s->mu);
          s->done = true;
          s->cv.notify_all();
        },
        &sync, nullptr, nullptr, 0, &wv, 1, /*priority=*/1, "WaitForVar");
    std::unique_lock<std::mutex> lk(sync.mu);
    sync.cv.wait(lk, [&] { return sync.done; });
  }

  void WaitAll() {
    std::unique_lock<std::mutex> lk(finish_mu_);
    finish_cv_.wait(lk, [this] {
      return pending_.load(std::memory_order_acquire) == 0;
    });
  }

  void SetProfiling(bool on) {
    std::lock_guard<std::mutex> lk(prof_mu_);
    profiling_ = on;
  }

  std::string DumpProfile() {
    std::lock_guard<std::mutex> lk(prof_mu_);
    std::string out = "[";
    for (size_t i = 0; i < events_.size(); ++i) {
      const ProfileEvent &e = events_[i];
      if (i) out += ",";
      out += "{\"name\":\"" + JsonEscape(e.name) +
             "\",\"cat\":\"engine\",\"ph\":\"X\"";
      out += ",\"ts\":" + std::to_string(e.start_us);
      out += ",\"dur\":" + std::to_string(e.dur_us);
      out += ",\"pid\":0,\"tid\":" + std::to_string(e.tid) + "}";
    }
    out += "]";
    events_.clear();
    return out;
  }

 private:
  void Dispatch(Opr *op) {
    if (naive_) {
      Execute(op);
      return;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (op->priority > 0) {
        hi_queue_.push_back(op);
      } else {
        queue_.push_back(op);
      }
    }
    cv_.notify_one();
  }

  void WorkerLoop() {
    for (;;) {
      Opr *op = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] {
          return shutdown_ || !queue_.empty() || !hi_queue_.empty();
        });
        if (shutdown_ && queue_.empty() && hi_queue_.empty()) return;
        if (!hi_queue_.empty()) {
          op = hi_queue_.front();
          hi_queue_.pop_front();
        } else {
          op = queue_.front();
          queue_.pop_front();
        }
      }
      Execute(op);
    }
  }

  static uint64_t NowUs() {
    return static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::microseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count());
  }

  void Execute(Opr *op) {
    bool prof;
    {
      std::lock_guard<std::mutex> lk(prof_mu_);
      prof = profiling_;
    }
    uint64_t t0 = prof ? NowUs() : 0;
    if (op->fn) op->fn(op->ctx);
    if (prof) {
      uint64_t t1 = NowUs();
      std::lock_guard<std::mutex> lk(prof_mu_);
      events_.push_back({op->name.empty() ? "op" : op->name,
                         std::hash<std::thread::id>()(
                             std::this_thread::get_id()) %
                             4096,
                         t0, t1 - t0});
    }
    OnComplete(op);
  }

  void OnComplete(Opr *op) {
    std::vector<Opr *> ready;
    for (Var *v : op->reads) Release(v, /*write=*/false, &ready);
    for (Var *v : op->writes) Release(v, /*write=*/true, &ready);
    if (op->on_complete) op->on_complete(op->ctx, /*cancelled=*/0);
    delete op;
    if (pending_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      std::lock_guard<std::mutex> lk(finish_mu_);
      finish_cv_.notify_all();
    }
    for (Opr *r : ready) Dispatch(r);
  }

  void Release(Var *v, bool write, std::vector<Opr *> *ready) {
    bool del = false;
    {
      std::lock_guard<std::mutex> lk(v->mu);
      if (write) {
        v->active_write = false;
      } else {
        --v->active_reads;
      }
      v->Grant(ready);
      del = v->to_delete && v->queue.empty() && v->active_reads == 0 &&
            !v->active_write;
    }
    if (del) delete v;
  }

  bool naive_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Opr *> queue_;
  std::deque<Opr *> hi_queue_;
  bool shutdown_ = false;

  std::atomic<int64_t> pending_{0};
  std::mutex finish_mu_;
  std::condition_variable finish_cv_;

  std::mutex prof_mu_;
  bool profiling_ = false;
  std::vector<ProfileEvent> events_;
};

void Var::Grant(std::vector<Opr *> *ready) {
  /* FIFO: grant a run of reads, or one write when fully drained. */
  while (!queue.empty()) {
    Pending &head = queue.front();
    if (head.write) {
      if (active_reads > 0 || active_write) break;
      active_write = true;
    } else {
      if (active_write) break;
      ++active_reads;
    }
    Opr *op = head.op;
    queue.pop_front();
    if (op->wait_count.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      ready->push_back(op);
    }
    if (active_write) break; /* writer granted exclusively */
  }
}

}  // namespace
}  // namespace mxtpu

using mxtpu::SetLastError;

#define API_BEGIN() try {
#define API_END()                        \
  }                                      \
  catch (const std::exception &e) {      \
    SetLastError(e.what());              \
    return -1;                           \
  }                                      \
  catch (...) {                          \
    SetLastError("unknown C++ error");   \
    return -1;                           \
  }                                      \
  return 0;

extern "C" {

int MXEngineCreate(int num_workers, int naive, EngineHandle *out) {
  API_BEGIN();
  *out = new mxtpu::Engine(num_workers, naive != 0);
  API_END();
}

int MXEngineFree(EngineHandle h) {
  API_BEGIN();
  delete static_cast<mxtpu::Engine *>(h);
  API_END();
}

int MXEngineNewVar(EngineHandle h, EngineVarHandle *out) {
  API_BEGIN();
  *out = static_cast<mxtpu::Engine *>(h)->NewVar();
  API_END();
}

int MXEngineFreeVar(EngineHandle h, EngineVarHandle var) {
  API_BEGIN();
  static_cast<mxtpu::Engine *>(h)->FreeVar(static_cast<mxtpu::Var *>(var));
  API_END();
}

int MXEnginePushAsync(EngineHandle h, MXEngineFn fn, void *ctx,
                      MXEngineOnComplete on_complete,
                      EngineVarHandle *read_vars, int n_read,
                      EngineVarHandle *write_vars, int n_write, int priority,
                      const char *name) {
  API_BEGIN();
  static_cast<mxtpu::Engine *>(h)->Push(fn, ctx, on_complete, read_vars,
                                        n_read, write_vars, n_write,
                                        priority, name);
  API_END();
}

int MXEngineWaitForVar(EngineHandle h, EngineVarHandle var) {
  API_BEGIN();
  static_cast<mxtpu::Engine *>(h)->WaitForVar(
      static_cast<mxtpu::Var *>(var));
  API_END();
}

int MXEngineWaitAll(EngineHandle h) {
  API_BEGIN();
  static_cast<mxtpu::Engine *>(h)->WaitAll();
  API_END();
}

int MXEngineSetProfiling(EngineHandle h, int enabled) {
  API_BEGIN();
  static_cast<mxtpu::Engine *>(h)->SetProfiling(enabled != 0);
  API_END();
}

int MXEngineDumpProfile(EngineHandle h, char **out_json) {
  API_BEGIN();
  std::string s = static_cast<mxtpu::Engine *>(h)->DumpProfile();
  char *buf = static_cast<char *>(std::malloc(s.size() + 1));
  std::memcpy(buf, s.c_str(), s.size() + 1);
  *out_json = buf;
  API_END();
}

int MXFreeString(char *s) {
  std::free(s);
  return 0;
}

}  // extern "C"
