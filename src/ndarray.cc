/*
 * ndarray.cc — NDArray C surface of the native runtime.
 *
 * Reference parity (leezu/mxnet): src/c_api/c_api_ndarray.cc +
 * src/ndarray/ndarray.cc (handle-based tensors, Imperative::Invoke ->
 * PushFCompute through the dependency engine, NDArray::Save/Load).
 *
 * Host tensors over the pooled storage manager; ops execute as closures
 * pushed to the shared dependency engine with read/write var discipline,
 * so the C surface exhibits the same async semantics as the reference
 * (create returns immediately, WaitToRead is the sync point).  The
 * accelerator op set stays behind the Python/XLA path by design; these
 * are the native kernels runnable without a Python interpreter.
 * Serialization is byte-compatible with mxnet_tpu/ndarray_io.py
 * (MXTPU001 container).
 */
#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <vector>

#include "./mxtpu.h"

namespace mxtpu {
void SetLastError(const std::string &msg);
namespace nd {

struct DTypeInfo {
  const char *np_str;  /* numpy dtype tag used by ndarray_io.py */
  size_t size;
};

/* reference mshadow type codes */
static const std::map<int, DTypeInfo> kDTypes = {
    {0, {"<f4", 4}}, {1, {"<f8", 8}}, {3, {"|u1", 1}},
    {4, {"<i4", 4}}, {6, {"<i8", 8}}, {12, {"bfloat16", 2}},
};

static int DTypeFromString(const std::string &s) {
  for (const auto &kv : kDTypes) {
    if (s == kv.second.np_str) return kv.first;
  }
  /* ndarray_io also writes e.g. "float32" style? no — numpy .str tags or
   * "bfloat16"; reject anything else */
  throw std::runtime_error("unsupported dtype tag '" + s + "'");
}

struct Array {
  std::vector<int64_t> shape;
  int dtype;
  void *data;          /* pooled host buffer */
  size_t nbytes;
  EngineVarHandle var; /* engine dependency var */
};

/* one shared engine + lock for the op path */
static EngineHandle g_engine = nullptr;
static std::mutex g_mu;

static EngineHandle Eng() {
  std::lock_guard<std::mutex> lk(g_mu);
  if (g_engine == nullptr) {
    if (MXEngineCreate(0, 0, &g_engine) != 0)
      throw std::runtime_error("engine creation failed");
  }
  return g_engine;
}

static Array *Cast(NDArrayHandle h) {
  if (h == nullptr) throw std::runtime_error("null NDArrayHandle");
  return static_cast<Array *>(h);
}

static uint64_t NumElems(const Array *a) {
  uint64_t n = 1;
  for (int64_t s : a->shape) n *= static_cast<uint64_t>(s);
  return n;
}

static Array *NewArray(const int64_t *shape, int ndim, int dtype) {
  auto it = kDTypes.find(dtype);
  if (it == kDTypes.end())
    throw std::runtime_error("unsupported dtype code " +
                             std::to_string(dtype));
  auto *a = new Array();
  a->shape.assign(shape, shape + ndim);
  a->dtype = dtype;
  uint64_t n = 1;
  for (int i = 0; i < ndim; ++i) {
    if (shape[i] < 0) throw std::runtime_error("negative dim");
    n *= static_cast<uint64_t>(shape[i]);
  }
  a->nbytes = n * it->second.size;
  if (MXStorageAlloc(a->nbytes ? a->nbytes : 1, &a->data) != 0)
    throw std::runtime_error("storage alloc failed");
  if (MXEngineNewVar(Eng(), &a->var) != 0)
    throw std::runtime_error("engine var failed");
  return a;
}

static void FreeArray(Array *a) {
  /* var free waits for pending ops touching the array */
  MXEngineFreeVar(g_engine ? g_engine : Eng(), a->var);
  MXStorageFree(a->data);
  delete a;
}

/* ---- native op kernels ------------------------------------------------ */

using OpFn = std::function<void(const std::vector<Array *> &,
                                const std::vector<Array *> &)>;
/* Shape/dtype validation runs SYNCHRONOUSLY in MXImperativeInvoke before
 * the push — an exception on an engine worker thread would terminate the
 * process, never reach MXGetLastError.  Kernels assume validated args. */
using Validator = std::function<void(const std::vector<Array *> &,
                                     const std::vector<Array *> &)>;

static void CheckSameShape(const std::vector<Array *> &in,
                           const std::vector<Array *> &out) {
  for (const Array *a : in)
    if (a->shape != in[0]->shape)
      throw std::runtime_error("elementwise op: shape mismatch");
  if (out[0]->shape != in[0]->shape)
    throw std::runtime_error("elementwise op: output shape mismatch");
  for (const Array *a : in)
    if (a->dtype != 0)
      throw std::runtime_error("native kernels are float32-only");
  if (out[0]->dtype != 0)
    throw std::runtime_error("native kernels are float32-only");
}

template <typename F>
static OpFn Elemwise2(F f) {
  return [f](const std::vector<Array *> &in,
             const std::vector<Array *> &out) {
    const float *a = static_cast<const float *>(in[0]->data);
    const float *b = static_cast<const float *>(in[1]->data);
    float *o = static_cast<float *>(out[0]->data);
    uint64_t n = NumElems(in[0]);
    for (uint64_t i = 0; i < n; ++i) o[i] = f(a[i], b[i]);
  };
}

template <typename F>
static OpFn Elemwise1(F f) {
  return [f](const std::vector<Array *> &in,
             const std::vector<Array *> &out) {
    const float *a = static_cast<const float *>(in[0]->data);
    float *o = static_cast<float *>(out[0]->data);
    uint64_t n = NumElems(in[0]);
    for (uint64_t i = 0; i < n; ++i) o[i] = f(a[i]);
  };
}

static void ValidateDot(const std::vector<Array *> &in,
                        const std::vector<Array *> &out) {
  const Array *A = in[0], *B = in[1], *C = out[0];
  if (A->shape.size() != 2 || B->shape.size() != 2 ||
      A->shape[1] != B->shape[0])
    throw std::runtime_error("dot: need (m,k)x(k,n) 2-D operands");
  if (C->shape.size() != 2 || C->shape[0] != A->shape[0] ||
      C->shape[1] != B->shape[1])
    throw std::runtime_error("dot: bad output shape");
  if (A->dtype != 0 || B->dtype != 0 || C->dtype != 0)
    throw std::runtime_error("dot: float32 only");
}

static void DotOp(const std::vector<Array *> &in,
                  const std::vector<Array *> &out) {
  const Array *A = in[0], *B = in[1];
  Array *C = out[0];
  int64_t m = A->shape[0], k = A->shape[1], n = B->shape[1];
  const float *a = static_cast<const float *>(A->data);
  const float *b = static_cast<const float *>(B->data);
  float *c = static_cast<float *>(C->data);
  std::memset(c, 0, sizeof(float) * m * n);
  for (int64_t i = 0; i < m; ++i)
    for (int64_t l = 0; l < k; ++l) {
      float av = a[i * k + l];
      const float *brow = b + l * n;
      float *crow = c + i * n;
      for (int64_t j = 0; j < n; ++j) crow[j] += av * brow[j];
    }
}

static void ValidateSum(const std::vector<Array *> &in,
                        const std::vector<Array *> &out) {
  if (in[0]->dtype != 0 || out[0]->dtype != 0)
    throw std::runtime_error("sum: float32 only");
  if (NumElems(out[0]) != 1)
    throw std::runtime_error("sum: scalar output expected");
}

static void SumOp(const std::vector<Array *> &in,
                  const std::vector<Array *> &out) {
  const float *a = static_cast<const float *>(in[0]->data);
  double acc = 0.0;
  uint64_t n = NumElems(in[0]);
  for (uint64_t i = 0; i < n; ++i) acc += a[i];
  *static_cast<float *>(out[0]->data) = static_cast<float>(acc);
}

static void ValidateCopy(const std::vector<Array *> &in,
                         const std::vector<Array *> &out) {
  if (in[0]->nbytes != out[0]->nbytes)
    throw std::runtime_error("copy: size mismatch");
}

static void CopyOp(const std::vector<Array *> &in,
                   const std::vector<Array *> &out) {
  std::memcpy(out[0]->data, in[0]->data, in[0]->nbytes);
}

struct OpEntry {
  int n_in, n_out;
  Validator validate;
  OpFn fn;
};

static const std::map<std::string, OpEntry> &Ops() {
  static const std::map<std::string, OpEntry> ops = {
      {"add",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a + b; })}},
      {"sub",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a - b; })}},
      {"mul",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a * b; })}},
      {"div",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a / b; })}},
      {"maximum",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a > b ? a : b; })}},
      {"relu",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return a > 0 ? a : 0.f; })}},
      {"exp",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return std::exp(a); })}},
      {"sqrt",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return std::sqrt(a); })}},
      {"negative",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return -a; })}},
      {"dot", {2, 1, ValidateDot, DotOp}},
      {"sum", {1, 1, ValidateSum, SumOp}},
      {"copy", {1, 1, ValidateCopy, CopyOp}},
  };
  return ops;
}

/* engine closure ctx */
struct InvokeCtx {
  OpFn fn;
  std::vector<Array *> in, out;
};

/* Async-kernel failures cannot throw across the engine worker thread;
 * record the first one here and rethrow at the next sync point
 * (WaitToRead/WaitAll/SyncCopy) — the reference engine's
 * OnCompleteStatic exception-propagation contract. */
static std::mutex async_err_mu;
static std::string async_err_msg;
static std::atomic<bool> async_err_set{false};

static void RecordAsyncError(const std::string &msg) {
  std::lock_guard<std::mutex> lk(async_err_mu);
  if (!async_err_set.load(std::memory_order_relaxed)) {
    async_err_msg = msg;
    async_err_set.store(true, std::memory_order_release);
  }
}

static void RethrowAsyncError() {
  if (!async_err_set.load(std::memory_order_acquire)) return;
  std::string msg;
  {
    std::lock_guard<std::mutex> lk(async_err_mu);
    /* recheck under the lock: a concurrent sync point may have consumed
     * the error between the fast check above and acquiring the mutex */
    if (!async_err_set.load(std::memory_order_relaxed)) return;
    msg = async_err_msg;
    async_err_set.store(false, std::memory_order_release);
  }
  throw std::runtime_error("async kernel failed: " + msg);
}

static void RunInvoke(void *p) {
  auto *ctx = static_cast<InvokeCtx *>(p);
  try {
    ctx->fn(ctx->in, ctx->out);
  } catch (const std::exception &e) {
    /* worker thread: record for the next sync point instead of
     * std::terminate (validation runs synchronously pre-push, so this
     * catches kernel/allocation failures only) */
    RecordAsyncError(e.what());
  } catch (...) {
    RecordAsyncError("unknown error");
  }
}

static void DoneInvoke(void *p, int /*cancelled*/) {
  delete static_cast<InvokeCtx *>(p);
}

/* ---- .params container (mirror of ndarray_io.py) ---------------------- */

static const char kMagic[8] = {'M', 'X', 'T', 'P', 'U', '0', '0', '1'};
static const size_t kAlign = 64;

static void WriteAll(FILE *f, const void *p, size_t n) {
  if (n && std::fwrite(p, 1, n, f) != n)
    throw std::runtime_error("short write");
}

static void ReadAll(FILE *f, void *p, size_t n) {
  if (n && std::fread(p, 1, n, f) != n)
    throw std::runtime_error("short read / truncated file");
}

}  // namespace nd
}  // namespace mxtpu

using mxtpu::SetLastError;
using namespace mxtpu::nd;  // NOLINT

#define API_BEGIN() try {
#define API_END()                      \
  }                                    \
  catch (const std::exception &e) {    \
    SetLastError(e.what());            \
    return -1;                         \
  }                                    \
  catch (...) {                        \
    SetLastError("unknown C++ error"); \
    return -1;                         \
  }                                    \
  return 0;

extern "C" {

int MXNDArrayCreate(const int64_t *shape, int ndim, int dtype,
                    NDArrayHandle *out) {
  API_BEGIN();
  *out = NewArray(shape, ndim, dtype);
  API_END();
}

int MXNDArrayFree(NDArrayHandle h) {
  API_BEGIN();
  FreeArray(Cast(h));
  API_END();
}

int MXNDArrayGetShape(NDArrayHandle h, int *out_ndim,
                      const int64_t **out_shape) {
  API_BEGIN();
  Array *a = Cast(h);
  *out_ndim = static_cast<int>(a->shape.size());
  *out_shape = a->shape.data();
  API_END();
}

int MXNDArrayGetDType(NDArrayHandle h, int *out_dtype) {
  API_BEGIN();
  *out_dtype = Cast(h)->dtype;
  API_END();
}

int MXNDArraySize(NDArrayHandle h, uint64_t *out_size) {
  API_BEGIN();
  *out_size = NumElems(Cast(h));
  API_END();
}

int MXNDArrayWaitToRead(NDArrayHandle h) {
  API_BEGIN();
  if (MXEngineWaitForVar(Eng(), Cast(h)->var) != 0)
    throw std::runtime_error(MXGetLastError());
  RethrowAsyncError();
  API_END();
}

int MXNDArrayWaitAll(void) {
  API_BEGIN();
  if (MXEngineWaitAll(Eng()) != 0)
    throw std::runtime_error(MXGetLastError());
  RethrowAsyncError();
  API_END();
}

int MXNDArrayGetData(NDArrayHandle h, void **out) {
  API_BEGIN();
  *out = Cast(h)->data;
  API_END();
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                             uint64_t nbytes) {
  API_BEGIN();
  Array *a = Cast(h);
  if (nbytes != a->nbytes)
    throw std::runtime_error("size mismatch in SyncCopyFromCPU");
  /* writer: wait for readers/writers, then copy on the caller thread */
  if (MXEngineWaitForVar(Eng(), a->var) != 0)
    throw std::runtime_error(MXGetLastError());
  std::memcpy(a->data, data, nbytes);
  API_END();
}

int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *data, uint64_t nbytes) {
  API_BEGIN();
  Array *a = Cast(h);
  if (nbytes != a->nbytes)
    throw std::runtime_error("size mismatch in SyncCopyToCPU");
  if (MXEngineWaitForVar(Eng(), a->var) != 0)
    throw std::runtime_error(MXGetLastError());
  RethrowAsyncError();
  std::memcpy(data, a->data, nbytes);
  API_END();
}

int MXImperativeInvoke(const char *op_name, NDArrayHandle *inputs, int n_in,
                       NDArrayHandle *outputs, int n_out) {
  API_BEGIN();
  const auto &ops = Ops();
  auto it = ops.find(op_name ? op_name : "");
  if (it == ops.end())
    throw std::runtime_error(std::string("unknown native op '") +
                             (op_name ? op_name : "<null>") + "'");
  if (n_in != it->second.n_in || n_out != it->second.n_out)
    throw std::runtime_error("op arity mismatch");
  {
    /* synchronous shape/dtype validation — errors must surface through
     * the MXGetLastError trampoline, not an engine worker thread */
    std::vector<Array *> vin, vout;
    for (int i = 0; i < n_in; ++i) vin.push_back(Cast(inputs[i]));
    for (int i = 0; i < n_out; ++i) vout.push_back(Cast(outputs[i]));
    it->second.validate(vin, vout);
  }
  auto *ctx = new InvokeCtx();
  ctx->fn = it->second.fn;
  std::vector<EngineVarHandle> rvars, wvars;
  for (int i = 0; i < n_in; ++i) {
    ctx->in.push_back(Cast(inputs[i]));
    rvars.push_back(ctx->in.back()->var);
  }
  for (int i = 0; i < n_out; ++i) {
    ctx->out.push_back(Cast(outputs[i]));
    wvars.push_back(ctx->out.back()->var);
  }
  if (MXEnginePushAsync(Eng(), RunInvoke, ctx, DoneInvoke, rvars.data(),
                        n_in, wvars.data(), n_out, 0, op_name) != 0) {
    delete ctx;
    throw std::runtime_error(MXGetLastError());
  }
  API_END();
}

int MXListAllOpNames(int *out_n, const char ***out_names) {
  API_BEGIN();
  static std::vector<const char *> names;
  if (names.empty())
    for (const auto &kv : Ops()) names.push_back(kv.first.c_str());
  *out_n = static_cast<int>(names.size());
  *out_names = names.data();
  API_END();
}

int MXNDArraySave(const char *fname, int num, NDArrayHandle *handles,
                  const char **names) {
  API_BEGIN();
  std::unique_ptr<FILE, int (*)(FILE *)> f(std::fopen(fname, "wb"),
                                           std::fclose);
  if (!f) throw std::runtime_error(std::string("cannot open ") + fname);
  WriteAll(f.get(), kMagic, 8);
  uint64_t cnt = static_cast<uint64_t>(num);
  WriteAll(f.get(), &cnt, 8);
  for (int i = 0; i < num; ++i) {
    Array *a = Cast(handles[i]);
    if (MXEngineWaitForVar(Eng(), a->var) != 0)
      throw std::runtime_error(MXGetLastError());
    const std::string name = names[i];
    const std::string dt = kDTypes.at(a->dtype).np_str;
    uint32_t nl = static_cast<uint32_t>(name.size());
    uint32_t dl = static_cast<uint32_t>(dt.size());
    WriteAll(f.get(), &nl, 4);
    WriteAll(f.get(), name.data(), nl);
    WriteAll(f.get(), &dl, 4);
    WriteAll(f.get(), dt.data(), dl);
    uint32_t nd = static_cast<uint32_t>(a->shape.size());
    WriteAll(f.get(), &nd, 4);
    for (int64_t s : a->shape) WriteAll(f.get(), &s, 8);
    long pos = std::ftell(f.get());
    size_t pad = (kAlign - static_cast<size_t>(pos) % kAlign) % kAlign;
    static const char zeros[kAlign] = {0};
    WriteAll(f.get(), zeros, pad);
    WriteAll(f.get(), a->data, a->nbytes);
  }
  API_END();
}

int MXNDArrayLoad(const char *fname, int *out_num,
                  NDArrayHandle **out_handles, char ***out_names) {
  API_BEGIN();
  std::unique_ptr<FILE, int (*)(FILE *)> f(std::fopen(fname, "rb"),
                                           std::fclose);
  if (!f) throw std::runtime_error(std::string("cannot open ") + fname);
  char magic[8];
  ReadAll(f.get(), magic, 8);
  if (std::memcmp(magic, kMagic, 8) != 0)
    throw std::runtime_error("bad magic: not an MXTPU001 .params file");
  uint64_t cnt = 0;
  ReadAll(f.get(), &cnt, 8);
  std::vector<NDArrayHandle> handles;
  std::vector<char *> names;
  try {
    for (uint64_t i = 0; i < cnt; ++i) {
      uint32_t nl = 0, dl = 0, nd = 0;
      ReadAll(f.get(), &nl, 4);
      std::string name(nl, '\0');
      ReadAll(f.get(), name.data(), nl);
      ReadAll(f.get(), &dl, 4);
      std::string dt(dl, '\0');
      ReadAll(f.get(), dt.data(), dl);
      ReadAll(f.get(), &nd, 4);
      std::vector<int64_t> shape(nd);
      for (uint32_t d = 0; d < nd; ++d) ReadAll(f.get(), &shape[d], 8);
      long pos = std::ftell(f.get());
      size_t pad = (kAlign - static_cast<size_t>(pos) % kAlign) % kAlign;
      if (pad) std::fseek(f.get(), static_cast<long>(pad), SEEK_CUR);
      Array *a = NewArray(shape.data(), static_cast<int>(nd),
                          DTypeFromString(dt));
      handles.push_back(a);
      ReadAll(f.get(), a->data, a->nbytes);
      char *nm = static_cast<char *>(std::malloc(nl + 1));
      std::memcpy(nm, name.data(), nl);
      nm[nl] = '\0';
      names.push_back(nm);
    }
  } catch (...) {
    for (NDArrayHandle h : handles) FreeArray(Cast(h));
    for (char *nm : names) std::free(nm);
    throw;
  }
  *out_num = static_cast<int>(cnt);
  *out_handles =
      static_cast<NDArrayHandle *>(std::malloc(sizeof(void *) * cnt));
  *out_names = static_cast<char **>(std::malloc(sizeof(char *) * cnt));
  std::copy(handles.begin(), handles.end(), *out_handles);
  std::copy(names.begin(), names.end(), *out_names);
  API_END();
}

int MXNDArrayLoadFree(int num, NDArrayHandle *handles, char **names) {
  API_BEGIN();
  for (int i = 0; i < num; ++i) std::free(names[i]);
  std::free(handles);
  std::free(names);
  API_END();
}

}  // extern "C"
