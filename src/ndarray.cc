/*
 * ndarray.cc — NDArray C surface of the native runtime.
 *
 * Reference parity (leezu/mxnet): src/c_api/c_api_ndarray.cc +
 * src/ndarray/ndarray.cc (handle-based tensors, Imperative::Invoke ->
 * PushFCompute through the dependency engine, NDArray::Save/Load).
 *
 * Host tensors over the pooled storage manager; ops execute as closures
 * pushed to the shared dependency engine with read/write var discipline,
 * so the C surface exhibits the same async semantics as the reference
 * (create returns immediately, WaitToRead is the sync point).  The
 * accelerator op set stays behind the Python/XLA path by design; these
 * are the native kernels runnable without a Python interpreter.
 * Serialization is byte-compatible with mxnet_tpu/ndarray_io.py
 * (MXTPU001 container).
 */
#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <limits>
#include <map>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <vector>

#include "./mxtpu.h"

namespace mxtpu {
void SetLastError(const std::string &msg);
namespace nd {

struct DTypeInfo {
  const char *np_str;  /* numpy dtype tag used by ndarray_io.py */
  size_t size;
};

/* reference mshadow type codes */
static const std::map<int, DTypeInfo> kDTypes = {
    {0, {"<f4", 4}}, {1, {"<f8", 8}}, {3, {"|u1", 1}},
    {4, {"<i4", 4}}, {6, {"<i8", 8}}, {12, {"bfloat16", 2}},
};

static int DTypeFromString(const std::string &s) {
  for (const auto &kv : kDTypes) {
    if (s == kv.second.np_str) return kv.first;
  }
  /* ndarray_io also writes e.g. "float32" style? no — numpy .str tags or
   * "bfloat16"; reject anything else */
  throw std::runtime_error("unsupported dtype tag '" + s + "'");
}

struct Array {
  std::vector<int64_t> shape;
  int dtype;
  void *data;          /* pooled host buffer */
  size_t nbytes;
  EngineVarHandle var; /* engine dependency var */
};

/* one shared engine + lock for the op path */
static EngineHandle g_engine = nullptr;
static std::mutex g_mu;

static EngineHandle Eng() {
  std::lock_guard<std::mutex> lk(g_mu);
  if (g_engine == nullptr) {
    if (MXEngineCreate(0, 0, &g_engine) != 0)
      throw std::runtime_error("engine creation failed");
  }
  return g_engine;
}

static Array *Cast(NDArrayHandle h) {
  if (h == nullptr) throw std::runtime_error("null NDArrayHandle");
  return static_cast<Array *>(h);
}

static uint64_t NumElems(const Array *a) {
  uint64_t n = 1;
  for (int64_t s : a->shape) n *= static_cast<uint64_t>(s);
  return n;
}

static Array *NewArray(const int64_t *shape, int ndim, int dtype) {
  auto it = kDTypes.find(dtype);
  if (it == kDTypes.end())
    throw std::runtime_error("unsupported dtype code " +
                             std::to_string(dtype));
  auto *a = new Array();
  a->shape.assign(shape, shape + ndim);
  a->dtype = dtype;
  uint64_t n = 1;
  for (int i = 0; i < ndim; ++i) {
    if (shape[i] < 0) throw std::runtime_error("negative dim");
    n *= static_cast<uint64_t>(shape[i]);
  }
  a->nbytes = n * it->second.size;
  if (MXStorageAlloc(a->nbytes ? a->nbytes : 1, &a->data) != 0)
    throw std::runtime_error("storage alloc failed");
  if (MXEngineNewVar(Eng(), &a->var) != 0)
    throw std::runtime_error("engine var failed");
  return a;
}

static void FreeArray(Array *a) {
  /* var free waits for pending ops touching the array */
  MXEngineFreeVar(g_engine ? g_engine : Eng(), a->var);
  MXStorageFree(a->data);
  delete a;
}

/* ---- native op kernels ------------------------------------------------ */

using OpFn = std::function<void(const std::vector<Array *> &,
                                const std::vector<Array *> &)>;
/* Shape/dtype validation runs SYNCHRONOUSLY in MXImperativeInvoke before
 * the push — an exception on an engine worker thread would terminate the
 * process, never reach MXGetLastError.  Kernels assume validated args. */
using Validator = std::function<void(const std::vector<Array *> &,
                                     const std::vector<Array *> &)>;

static void CheckSameShape(const std::vector<Array *> &in,
                           const std::vector<Array *> &out) {
  for (const Array *a : in)
    if (a->shape != in[0]->shape)
      throw std::runtime_error("elementwise op: shape mismatch");
  if (out[0]->shape != in[0]->shape)
    throw std::runtime_error("elementwise op: output shape mismatch");
  for (const Array *a : in)
    if (a->dtype != 0)
      throw std::runtime_error("native kernels are float32-only");
  if (out[0]->dtype != 0)
    throw std::runtime_error("native kernels are float32-only");
}

template <typename F>
static OpFn Elemwise2(F f) {
  return [f](const std::vector<Array *> &in,
             const std::vector<Array *> &out) {
    const float *a = static_cast<const float *>(in[0]->data);
    const float *b = static_cast<const float *>(in[1]->data);
    float *o = static_cast<float *>(out[0]->data);
    uint64_t n = NumElems(in[0]);
    for (uint64_t i = 0; i < n; ++i) o[i] = f(a[i], b[i]);
  };
}

template <typename F>
static OpFn Elemwise1(F f) {
  return [f](const std::vector<Array *> &in,
             const std::vector<Array *> &out) {
    const float *a = static_cast<const float *>(in[0]->data);
    float *o = static_cast<float *>(out[0]->data);
    uint64_t n = NumElems(in[0]);
    for (uint64_t i = 0; i < n; ++i) o[i] = f(a[i]);
  };
}

static void ValidateDot(const std::vector<Array *> &in,
                        const std::vector<Array *> &out) {
  const Array *A = in[0], *B = in[1], *C = out[0];
  if (A->shape.size() != 2 || B->shape.size() != 2 ||
      A->shape[1] != B->shape[0])
    throw std::runtime_error("dot: need (m,k)x(k,n) 2-D operands");
  if (C->shape.size() != 2 || C->shape[0] != A->shape[0] ||
      C->shape[1] != B->shape[1])
    throw std::runtime_error("dot: bad output shape");
  if (A->dtype != 0 || B->dtype != 0 || C->dtype != 0)
    throw std::runtime_error("dot: float32 only");
}

static void DotOp(const std::vector<Array *> &in,
                  const std::vector<Array *> &out) {
  const Array *A = in[0], *B = in[1];
  Array *C = out[0];
  int64_t m = A->shape[0], k = A->shape[1], n = B->shape[1];
  const float *a = static_cast<const float *>(A->data);
  const float *b = static_cast<const float *>(B->data);
  float *c = static_cast<float *>(C->data);
  std::memset(c, 0, sizeof(float) * m * n);
  for (int64_t i = 0; i < m; ++i)
    for (int64_t l = 0; l < k; ++l) {
      float av = a[i * k + l];
      const float *brow = b + l * n;
      float *crow = c + i * n;
      for (int64_t j = 0; j < n; ++j) crow[j] += av * brow[j];
    }
}

static void ValidateSum(const std::vector<Array *> &in,
                        const std::vector<Array *> &out) {
  if (in[0]->dtype != 0 || out[0]->dtype != 0)
    throw std::runtime_error("sum: float32 only");
  if (NumElems(out[0]) != 1)
    throw std::runtime_error("sum: scalar output expected");
}

static void SumOp(const std::vector<Array *> &in,
                  const std::vector<Array *> &out) {
  const float *a = static_cast<const float *>(in[0]->data);
  double acc = 0.0;
  uint64_t n = NumElems(in[0]);
  for (uint64_t i = 0; i < n; ++i) acc += a[i];
  *static_cast<float *>(out[0]->data) = static_cast<float>(acc);
}

static void ValidateCopy(const std::vector<Array *> &in,
                         const std::vector<Array *> &out) {
  if (in[0]->nbytes != out[0]->nbytes)
    throw std::runtime_error("copy: size mismatch");
}

static void CopyOp(const std::vector<Array *> &in,
                   const std::vector<Array *> &out) {
  std::memcpy(out[0]->data, in[0]->data, in[0]->nbytes);
}

/* ---- deployment kernels (c_predict_api.cc analog op set) --------------
 * Ops with geometry take a trailing int32 attrs input array (the engine
 * path has no attribute channel; attrs ride as data, XLA-style). */

static void RequireF32(const Array *a, const char *who) {
  if (a->dtype != 0)
    throw std::runtime_error(std::string(who) + ": float32 only");
}

static void ValidateDense(const std::vector<Array *> &in,
                          const std::vector<Array *> &out) {
  const Array *x = in[0], *W = in[1], *b = in[2], *o = out[0];
  RequireF32(x, "dense"); RequireF32(W, "dense");
  RequireF32(b, "dense"); RequireF32(o, "dense");
  if (x->shape.size() != 2 || W->shape.size() != 2 ||
      x->shape[1] != W->shape[1])
    throw std::runtime_error("dense: need x (N,K) and W (U,K)");
  if (b->shape.size() != 1 || b->shape[0] != W->shape[0])
    throw std::runtime_error("dense: bias must be (U,)");
  if (o->shape.size() != 2 || o->shape[0] != x->shape[0] ||
      o->shape[1] != W->shape[0])
    throw std::runtime_error("dense: bad output shape");
}

static void DenseOp(const std::vector<Array *> &in,
                    const std::vector<Array *> &out) {
  const float *x = static_cast<const float *>(in[0]->data);
  const float *W = static_cast<const float *>(in[1]->data);
  const float *b = static_cast<const float *>(in[2]->data);
  float *o = static_cast<float *>(out[0]->data);
  int64_t N = in[0]->shape[0], K = in[0]->shape[1], U = in[1]->shape[0];
  for (int64_t i = 0; i < N; ++i)
    for (int64_t u = 0; u < U; ++u) {
      const float *xr = x + i * K, *wr = W + u * K;
      double acc = b[u];
      for (int64_t k = 0; k < K; ++k) acc += double(xr[k]) * wr[k];
      o[i * U + u] = static_cast<float>(acc);
    }
}

static void ValidateSoftmax(const std::vector<Array *> &in,
                            const std::vector<Array *> &out) {
  RequireF32(in[0], "softmax"); RequireF32(out[0], "softmax");
  if (in[0]->shape != out[0]->shape || in[0]->shape.empty())
    throw std::runtime_error("softmax: same-shape >=1-D in/out required");
}

static void SoftmaxOp(const std::vector<Array *> &in,
                      const std::vector<Array *> &out) {
  const float *x = static_cast<const float *>(in[0]->data);
  float *o = static_cast<float *>(out[0]->data);
  int64_t C = in[0]->shape.back();
  int64_t rows = static_cast<int64_t>(NumElems(in[0])) / (C ? C : 1);
  for (int64_t r = 0; r < rows; ++r) {
    const float *xr = x + r * C;
    float *orow = o + r * C;
    float mx = xr[0];
    for (int64_t c = 1; c < C; ++c) mx = std::max(mx, xr[c]);
    double sum = 0.0;
    for (int64_t c = 0; c < C; ++c) {
      orow[c] = std::exp(xr[c] - mx);
      sum += orow[c];
    }
    for (int64_t c = 0; c < C; ++c)
      orow[c] = static_cast<float>(orow[c] / sum);
  }
}

static void ValidateBNInf(const std::vector<Array *> &in,
                          const std::vector<Array *> &out) {
  const Array *x = in[0];
  for (const Array *a : in) RequireF32(a, "batchnorm_inf");
  RequireF32(out[0], "batchnorm_inf");
  if (x->shape.size() < 2)
    throw std::runtime_error("batchnorm_inf: need >= 2-D NC... input");
  int64_t C = x->shape[1];
  for (int i = 1; i <= 4; ++i)
    if (in[i]->shape.size() != 1 || in[i]->shape[0] != C)
      throw std::runtime_error("batchnorm_inf: stats must be (C,)");
  if (NumElems(in[5]) != 1)
    throw std::runtime_error("batchnorm_inf: eps must be a scalar array");
  if (out[0]->shape != x->shape)
    throw std::runtime_error("batchnorm_inf: output shape mismatch");
}

static void BNInfOp(const std::vector<Array *> &in,
                    const std::vector<Array *> &out) {
  const float *x = static_cast<const float *>(in[0]->data);
  const float *g = static_cast<const float *>(in[1]->data);
  const float *b = static_cast<const float *>(in[2]->data);
  const float *m = static_cast<const float *>(in[3]->data);
  const float *v = static_cast<const float *>(in[4]->data);
  float eps = *static_cast<const float *>(in[5]->data);
  float *o = static_cast<float *>(out[0]->data);
  int64_t N = in[0]->shape[0], C = in[0]->shape[1];
  int64_t inner = 1;
  for (size_t i = 2; i < in[0]->shape.size(); ++i)
    inner *= in[0]->shape[i];
  for (int64_t n = 0; n < N; ++n)
    for (int64_t c = 0; c < C; ++c) {
      float scale = g[c] / std::sqrt(v[c] + eps);
      float shift = b[c] - m[c] * scale;
      const float *xr = x + (n * C + c) * inner;
      float *orow = o + (n * C + c) * inner;
      for (int64_t i = 0; i < inner; ++i) orow[i] = xr[i] * scale + shift;
    }
}

static const int32_t *IntAttrs(const Array *a, size_t n, const char *who) {
  if (a->dtype != 4 || NumElems(a) != n)
    throw std::runtime_error(std::string(who) +
                             ": attrs must be int32[" + std::to_string(n) +
                             "]");
  return static_cast<const int32_t *>(a->data);
}

static void ValidateConv2D(const std::vector<Array *> &in,
                           const std::vector<Array *> &out) {
  const Array *x = in[0], *W = in[1], *b = in[2], *o = out[0];
  RequireF32(x, "conv2d"); RequireF32(W, "conv2d");
  RequireF32(b, "conv2d"); RequireF32(o, "conv2d");
  if (x->shape.size() != 4 || W->shape.size() != 4 ||
      x->shape[1] != W->shape[1])
    throw std::runtime_error("conv2d: need x NCHW and W OIHW");
  const int32_t *at = IntAttrs(in[3], 4, "conv2d");
  if (at[0] <= 0 || at[1] <= 0)
    throw std::runtime_error("conv2d: stride must be positive");
  int64_t OH = (x->shape[2] + 2 * at[2] - W->shape[2]) / at[0] + 1;
  int64_t OW = (x->shape[3] + 2 * at[3] - W->shape[3]) / at[1] + 1;
  std::vector<int64_t> want = {x->shape[0], W->shape[0], OH, OW};
  if (o->shape != want)
    throw std::runtime_error("conv2d: bad output shape");
  if (b->shape.size() != 1 || b->shape[0] != W->shape[0])
    throw std::runtime_error("conv2d: bias must be (O,)");
}

static void Conv2DOp(const std::vector<Array *> &in,
                     const std::vector<Array *> &out) {
  const float *x = static_cast<const float *>(in[0]->data);
  const float *W = static_cast<const float *>(in[1]->data);
  const float *b = static_cast<const float *>(in[2]->data);
  const int32_t *at = static_cast<const int32_t *>(in[3]->data);
  float *o = static_cast<float *>(out[0]->data);
  int64_t N = in[0]->shape[0], C = in[0]->shape[1];
  int64_t H = in[0]->shape[2], Wd = in[0]->shape[3];
  int64_t O = in[1]->shape[0], KH = in[1]->shape[2], KW = in[1]->shape[3];
  int64_t sh = at[0], sw = at[1], ph = at[2], pw = at[3];
  int64_t OH = out[0]->shape[2], OW = out[0]->shape[3];
  for (int64_t n = 0; n < N; ++n)
    for (int64_t oc = 0; oc < O; ++oc)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          double acc = b[oc];
          for (int64_t c = 0; c < C; ++c)
            for (int64_t kh = 0; kh < KH; ++kh) {
              int64_t ih = oh * sh - ph + kh;
              if (ih < 0 || ih >= H) continue;
              for (int64_t kw = 0; kw < KW; ++kw) {
                int64_t iw = ow * sw - pw + kw;
                if (iw < 0 || iw >= Wd) continue;
                acc += double(x[((n * C + c) * H + ih) * Wd + iw]) *
                       W[((oc * C + c) * KH + kh) * KW + kw];
              }
            }
          o[((n * O + oc) * OH + oh) * OW + ow] =
              static_cast<float>(acc);
        }
}

static void ValidatePool2D(const std::vector<Array *> &in,
                           const std::vector<Array *> &out) {
  const Array *x = in[0], *o = out[0];
  RequireF32(x, "pool2d"); RequireF32(o, "pool2d");
  if (x->shape.size() != 4)
    throw std::runtime_error("pool2d: need NCHW input");
  const int32_t *at = IntAttrs(in[1], 7, "pool2d");
  int64_t OH, OW;
  if (at[6] & 1) {                          /* global pool */
    OH = OW = 1;
  } else {
    if (at[0] <= 0 || at[1] <= 0)
      throw std::runtime_error("pool2d: kernel must be positive");
    if (at[2] <= 0 || at[3] <= 0)
      throw std::runtime_error("pool2d: stride must be positive");
    if (at[4] >= at[0] || at[5] >= at[1])
      throw std::runtime_error(
          "pool2d: padding must be smaller than the kernel");
    OH = (x->shape[2] + 2 * at[4] - at[0]) / at[2] + 1;
    OW = (x->shape[3] + 2 * at[5] - at[1]) / at[3] + 1;
  }
  std::vector<int64_t> want = {x->shape[0], x->shape[1], OH, OW};
  if (o->shape != want)
    throw std::runtime_error("pool2d: bad output shape");
}

template <bool MAX>
static void Pool2DOp(const std::vector<Array *> &in,
                     const std::vector<Array *> &out) {
  const float *x = static_cast<const float *>(in[0]->data);
  const int32_t *at = static_cast<const int32_t *>(in[1]->data);
  float *o = static_cast<float *>(out[0]->data);
  int64_t N = in[0]->shape[0], C = in[0]->shape[1];
  int64_t H = in[0]->shape[2], Wd = in[0]->shape[3];
  bool global = at[6] & 1, include_pad = at[6] & 2;
  int64_t kh = global ? H : at[0], kw = global ? Wd : at[1];
  int64_t sh = global ? 1 : at[2], sw = global ? 1 : at[3];
  int64_t ph = global ? 0 : at[4], pw = global ? 0 : at[5];
  int64_t OH = out[0]->shape[2], OW = out[0]->shape[3];
  for (int64_t n = 0; n < N; ++n)
    for (int64_t c = 0; c < C; ++c)
      for (int64_t oh = 0; oh < OH; ++oh)
        for (int64_t ow = 0; ow < OW; ++ow) {
          double acc =
              MAX ? -std::numeric_limits<double>::infinity() : 0.0;
          int64_t cnt = 0;
          for (int64_t i = 0; i < kh; ++i) {
            int64_t ih = oh * sh - ph + i;
            if (ih < 0 || ih >= H) continue;
            for (int64_t j = 0; j < kw; ++j) {
              int64_t iw = ow * sw - pw + j;
              if (iw < 0 || iw >= Wd) continue;
              float v = x[((n * C + c) * H + ih) * Wd + iw];
              if (MAX) acc = std::max(acc, double(v));
              else acc += v;
              ++cnt;
            }
          }
          if (!MAX) {
            int64_t denom = include_pad ? kh * kw : (cnt ? cnt : 1);
            acc /= denom;
          }
          o[((n * C + c) * OH + oh) * OW + ow] =
              static_cast<float>(acc);
        }
}

static void ValidateConcat(const std::vector<Array *> &in,
                           const std::vector<Array *> &out) {
  /* inputs: >=2 data arrays + one int32 axis attr (last) */
  if (in.size() < 3)
    throw std::runtime_error("concat: need >=2 inputs + axis attr");
  const Array *at = in.back();
  if (at->dtype != 4 || NumElems(at) != 1)
    throw std::runtime_error("concat: axis attr must be one int32");
  int axis = static_cast<const int32_t *>(at->data)[0];
  const Array *a0 = in[0];
  size_t nd = a0->shape.size();
  if (axis < 0 || static_cast<size_t>(axis) >= nd)
    throw std::runtime_error("concat: axis out of range");
  int64_t ax_sum = 0;
  for (size_t t = 0; t + 1 < in.size(); ++t) {
    const Array *a = in[t];
    if (a->dtype != 0)
      throw std::runtime_error("concat: float32 only");
    if (a->shape.size() != nd)
      throw std::runtime_error("concat: rank mismatch");
    for (size_t d = 0; d < nd; ++d)
      if (d != static_cast<size_t>(axis) && a->shape[d] != a0->shape[d])
        throw std::runtime_error("concat: non-axis dim mismatch");
    ax_sum += a->shape[axis];
  }
  if (out[0]->dtype != 0 || out[0]->shape.size() != nd ||
      out[0]->shape[axis] != ax_sum)
    throw std::runtime_error("concat: bad output shape");
  for (size_t d = 0; d < nd; ++d)
    if (d != static_cast<size_t>(axis) &&
        out[0]->shape[d] != a0->shape[d])
      throw std::runtime_error("concat: bad output shape");
}

static void ConcatOp(const std::vector<Array *> &in,
                     const std::vector<Array *> &out) {
  const Array *at = in.back();
  int axis = static_cast<const int32_t *>(at->data)[0];
  Array *O = out[0];
  size_t nd = O->shape.size();
  int64_t outer = 1, inner = 1;
  for (int d = 0; d < axis; ++d) outer *= O->shape[d];
  for (size_t d = axis + 1; d < nd; ++d) inner *= O->shape[d];
  float *o = static_cast<float *>(O->data);
  int64_t out_ax = O->shape[axis], off_ax = 0;
  for (size_t t = 0; t + 1 < in.size(); ++t) {
    const Array *A = in[t];
    int64_t ax = A->shape[axis];
    const float *a = static_cast<const float *>(A->data);
    for (int64_t ou = 0; ou < outer; ++ou)
      std::memcpy(o + (ou * out_ax + off_ax) * inner,
                  a + ou * ax * inner, sizeof(float) * ax * inner);
    off_ax += ax;
  }
}

struct OpEntry {
  int n_in, n_out;                 /* n_in < 0: variable (>= -n_in) */
  Validator validate;
  OpFn fn;
};

static const std::map<std::string, OpEntry> &Ops() {
  static const std::map<std::string, OpEntry> ops = {
      {"add",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a + b; })}},
      {"sub",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a - b; })}},
      {"mul",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a * b; })}},
      {"div",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a / b; })}},
      {"maximum",
       {2, 1, CheckSameShape,
        Elemwise2([](float a, float b) { return a > b ? a : b; })}},
      {"relu",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return a > 0 ? a : 0.f; })}},
      {"exp",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return std::exp(a); })}},
      {"sqrt",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return std::sqrt(a); })}},
      {"negative",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return -a; })}},
      {"sigmoid",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return 1.f / (1.f + std::exp(-a)); })}},
      {"tanh",
       {1, 1, CheckSameShape,
        Elemwise1([](float a) { return std::tanh(a); })}},
      {"dot", {2, 1, ValidateDot, DotOp}},
      {"sum", {1, 1, ValidateSum, SumOp}},
      {"copy", {1, 1, ValidateCopy, CopyOp}},
      /* deployment set (c_predict_api analog; see MXPredCreate) */
      {"dense", {3, 1, ValidateDense, DenseOp}},
      {"softmax", {1, 1, ValidateSoftmax, SoftmaxOp}},
      {"flatten", {1, 1, ValidateCopy, CopyOp}},
      {"batchnorm_inf", {6, 1, ValidateBNInf, BNInfOp}},
      {"conv2d", {4, 1, ValidateConv2D, Conv2DOp}},
      {"maxpool2d", {2, 1, ValidatePool2D, Pool2DOp<true>}},
      {"avgpool2d", {2, 1, ValidatePool2D, Pool2DOp<false>}},
      /* variable arity: N>=2 data inputs + int32 axis attr */
      {"concat", {-3, 1, ValidateConcat, ConcatOp}},
  };
  return ops;
}

/* engine closure ctx */
struct InvokeCtx {
  OpFn fn;
  std::vector<Array *> in, out;
};

/* Async-kernel failures cannot throw across the engine worker thread;
 * record the first one here and rethrow at the next sync point
 * (WaitToRead/WaitAll/SyncCopy) — the reference engine's
 * OnCompleteStatic exception-propagation contract. */
static std::mutex async_err_mu;
static std::string async_err_msg;
static std::atomic<bool> async_err_set{false};

static void RecordAsyncError(const std::string &msg) {
  std::lock_guard<std::mutex> lk(async_err_mu);
  if (!async_err_set.load(std::memory_order_relaxed)) {
    async_err_msg = msg;
    async_err_set.store(true, std::memory_order_release);
  }
}

static void RethrowAsyncError() {
  if (!async_err_set.load(std::memory_order_acquire)) return;
  std::string msg;
  {
    std::lock_guard<std::mutex> lk(async_err_mu);
    /* recheck under the lock: a concurrent sync point may have consumed
     * the error between the fast check above and acquiring the mutex */
    if (!async_err_set.load(std::memory_order_relaxed)) return;
    msg = async_err_msg;
    async_err_set.store(false, std::memory_order_release);
  }
  throw std::runtime_error("async kernel failed: " + msg);
}

static void RunInvoke(void *p) {
  auto *ctx = static_cast<InvokeCtx *>(p);
  try {
    ctx->fn(ctx->in, ctx->out);
  } catch (const std::exception &e) {
    /* worker thread: record for the next sync point instead of
     * std::terminate (validation runs synchronously pre-push, so this
     * catches kernel/allocation failures only) */
    RecordAsyncError(e.what());
  } catch (...) {
    RecordAsyncError("unknown error");
  }
}

static void DoneInvoke(void *p, int /*cancelled*/) {
  delete static_cast<InvokeCtx *>(p);
}

/* ---- .params container (mirror of ndarray_io.py) ---------------------- */

static const char kMagic[8] = {'M', 'X', 'T', 'P', 'U', '0', '0', '1'};
static const size_t kAlign = 64;

static void WriteAll(FILE *f, const void *p, size_t n) {
  if (n && std::fwrite(p, 1, n, f) != n)
    throw std::runtime_error("short write");
}

static void ReadAll(FILE *f, void *p, size_t n) {
  if (n && std::fread(p, 1, n, f) != n)
    throw std::runtime_error("short read / truncated file");
}

}  // namespace nd
}  // namespace mxtpu

using mxtpu::SetLastError;
using namespace mxtpu::nd;  // NOLINT

#define API_BEGIN() try {
#define API_END()                      \
  }                                    \
  catch (const std::exception &e) {    \
    SetLastError(e.what());            \
    return -1;                         \
  }                                    \
  catch (...) {                        \
    SetLastError("unknown C++ error"); \
    return -1;                         \
  }                                    \
  return 0;

extern "C" {

int MXNDArrayCreate(const int64_t *shape, int ndim, int dtype,
                    NDArrayHandle *out) {
  API_BEGIN();
  *out = NewArray(shape, ndim, dtype);
  API_END();
}

int MXNDArrayFree(NDArrayHandle h) {
  API_BEGIN();
  FreeArray(Cast(h));
  API_END();
}

int MXNDArrayGetShape(NDArrayHandle h, int *out_ndim,
                      const int64_t **out_shape) {
  API_BEGIN();
  Array *a = Cast(h);
  *out_ndim = static_cast<int>(a->shape.size());
  *out_shape = a->shape.data();
  API_END();
}

int MXNDArrayGetDType(NDArrayHandle h, int *out_dtype) {
  API_BEGIN();
  *out_dtype = Cast(h)->dtype;
  API_END();
}

int MXNDArraySize(NDArrayHandle h, uint64_t *out_size) {
  API_BEGIN();
  *out_size = NumElems(Cast(h));
  API_END();
}

int MXNDArrayWaitToRead(NDArrayHandle h) {
  API_BEGIN();
  if (MXEngineWaitForVar(Eng(), Cast(h)->var) != 0)
    throw std::runtime_error(MXGetLastError());
  RethrowAsyncError();
  API_END();
}

int MXNDArrayWaitAll(void) {
  API_BEGIN();
  if (MXEngineWaitAll(Eng()) != 0)
    throw std::runtime_error(MXGetLastError());
  RethrowAsyncError();
  API_END();
}

int MXNDArrayGetData(NDArrayHandle h, void **out) {
  API_BEGIN();
  *out = Cast(h)->data;
  API_END();
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                             uint64_t nbytes) {
  API_BEGIN();
  Array *a = Cast(h);
  if (nbytes != a->nbytes)
    throw std::runtime_error("size mismatch in SyncCopyFromCPU");
  /* writer: wait for readers/writers, then copy on the caller thread */
  if (MXEngineWaitForVar(Eng(), a->var) != 0)
    throw std::runtime_error(MXGetLastError());
  std::memcpy(a->data, data, nbytes);
  API_END();
}

int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *data, uint64_t nbytes) {
  API_BEGIN();
  Array *a = Cast(h);
  if (nbytes != a->nbytes)
    throw std::runtime_error("size mismatch in SyncCopyToCPU");
  if (MXEngineWaitForVar(Eng(), a->var) != 0)
    throw std::runtime_error(MXGetLastError());
  RethrowAsyncError();
  std::memcpy(data, a->data, nbytes);
  API_END();
}

int MXImperativeInvoke(const char *op_name, NDArrayHandle *inputs, int n_in,
                       NDArrayHandle *outputs, int n_out) {
  API_BEGIN();
  const auto &ops = Ops();
  auto it = ops.find(op_name ? op_name : "");
  if (it == ops.end())
    throw std::runtime_error(std::string("unknown native op '") +
                             (op_name ? op_name : "<null>") + "'");
  if (it->second.n_in >= 0 ? n_in != it->second.n_in
                           : n_in < -it->second.n_in)
    throw std::runtime_error("op arity mismatch");
  if (n_out != it->second.n_out)
    throw std::runtime_error("op arity mismatch");
  {
    /* synchronous shape/dtype validation — errors must surface through
     * the MXGetLastError trampoline, not an engine worker thread */
    std::vector<Array *> vin, vout;
    for (int i = 0; i < n_in; ++i) vin.push_back(Cast(inputs[i]));
    for (int i = 0; i < n_out; ++i) vout.push_back(Cast(outputs[i]));
    it->second.validate(vin, vout);
  }
  auto *ctx = new InvokeCtx();
  ctx->fn = it->second.fn;
  std::vector<EngineVarHandle> rvars, wvars;
  for (int i = 0; i < n_in; ++i) {
    ctx->in.push_back(Cast(inputs[i]));
    rvars.push_back(ctx->in.back()->var);
  }
  for (int i = 0; i < n_out; ++i) {
    ctx->out.push_back(Cast(outputs[i]));
    wvars.push_back(ctx->out.back()->var);
  }
  if (MXEnginePushAsync(Eng(), RunInvoke, ctx, DoneInvoke, rvars.data(),
                        n_in, wvars.data(), n_out, 0, op_name) != 0) {
    delete ctx;
    throw std::runtime_error(MXGetLastError());
  }
  API_END();
}

int MXListAllOpNames(int *out_n, const char ***out_names) {
  API_BEGIN();
  static std::vector<const char *> names;
  if (names.empty())
    for (const auto &kv : Ops()) names.push_back(kv.first.c_str());
  *out_n = static_cast<int>(names.size());
  *out_names = names.data();
  API_END();
}

int MXNDArraySave(const char *fname, int num, NDArrayHandle *handles,
                  const char **names) {
  API_BEGIN();
  std::unique_ptr<FILE, int (*)(FILE *)> f(std::fopen(fname, "wb"),
                                           std::fclose);
  if (!f) throw std::runtime_error(std::string("cannot open ") + fname);
  WriteAll(f.get(), kMagic, 8);
  uint64_t cnt = static_cast<uint64_t>(num);
  WriteAll(f.get(), &cnt, 8);
  for (int i = 0; i < num; ++i) {
    Array *a = Cast(handles[i]);
    if (MXEngineWaitForVar(Eng(), a->var) != 0)
      throw std::runtime_error(MXGetLastError());
    const std::string name = names[i];
    const std::string dt = kDTypes.at(a->dtype).np_str;
    uint32_t nl = static_cast<uint32_t>(name.size());
    uint32_t dl = static_cast<uint32_t>(dt.size());
    WriteAll(f.get(), &nl, 4);
    WriteAll(f.get(), name.data(), nl);
    WriteAll(f.get(), &dl, 4);
    WriteAll(f.get(), dt.data(), dl);
    uint32_t nd = static_cast<uint32_t>(a->shape.size());
    WriteAll(f.get(), &nd, 4);
    for (int64_t s : a->shape) WriteAll(f.get(), &s, 8);
    long pos = std::ftell(f.get());
    size_t pad = (kAlign - static_cast<size_t>(pos) % kAlign) % kAlign;
    static const char zeros[kAlign] = {0};
    WriteAll(f.get(), zeros, pad);
    WriteAll(f.get(), a->data, a->nbytes);
  }
  API_END();
}

int MXNDArrayLoad(const char *fname, int *out_num,
                  NDArrayHandle **out_handles, char ***out_names) {
  API_BEGIN();
  std::unique_ptr<FILE, int (*)(FILE *)> f(std::fopen(fname, "rb"),
                                           std::fclose);
  if (!f) throw std::runtime_error(std::string("cannot open ") + fname);
  char magic[8];
  ReadAll(f.get(), magic, 8);
  if (std::memcmp(magic, kMagic, 8) != 0)
    throw std::runtime_error("bad magic: not an MXTPU001 .params file");
  uint64_t cnt = 0;
  ReadAll(f.get(), &cnt, 8);
  std::vector<NDArrayHandle> handles;
  std::vector<char *> names;
  try {
    for (uint64_t i = 0; i < cnt; ++i) {
      uint32_t nl = 0, dl = 0, nd = 0;
      ReadAll(f.get(), &nl, 4);
      std::string name(nl, '\0');
      ReadAll(f.get(), name.data(), nl);
      ReadAll(f.get(), &dl, 4);
      std::string dt(dl, '\0');
      ReadAll(f.get(), dt.data(), dl);
      ReadAll(f.get(), &nd, 4);
      std::vector<int64_t> shape(nd);
      for (uint32_t d = 0; d < nd; ++d) ReadAll(f.get(), &shape[d], 8);
      long pos = std::ftell(f.get());
      size_t pad = (kAlign - static_cast<size_t>(pos) % kAlign) % kAlign;
      if (pad) std::fseek(f.get(), static_cast<long>(pad), SEEK_CUR);
      Array *a = NewArray(shape.data(), static_cast<int>(nd),
                          DTypeFromString(dt));
      handles.push_back(a);
      ReadAll(f.get(), a->data, a->nbytes);
      char *nm = static_cast<char *>(std::malloc(nl + 1));
      std::memcpy(nm, name.data(), nl);
      nm[nl] = '\0';
      names.push_back(nm);
    }
  } catch (...) {
    for (NDArrayHandle h : handles) FreeArray(Cast(h));
    for (char *nm : names) std::free(nm);
    throw;
  }
  *out_num = static_cast<int>(cnt);
  *out_handles =
      static_cast<NDArrayHandle *>(std::malloc(sizeof(void *) * cnt));
  *out_names = static_cast<char **>(std::malloc(sizeof(char *) * cnt));
  std::copy(handles.begin(), handles.end(), *out_handles);
  std::copy(names.begin(), names.end(), *out_names);
  API_END();
}

int MXNDArrayLoadFree(int num, NDArrayHandle *handles, char **names) {
  API_BEGIN();
  for (int i = 0; i < num; ++i) std::free(names[i]);
  std::free(handles);
  std::free(names);
  API_END();
}

}  // extern "C"
