/*
 * predict.cc — C deployment path (reference: src/c_predict_api.cc).
 *
 * Loads a model exported by HybridBlock.export() — the symbol json's
 * "deploy_graph" layer-op list plus the .params file — and runs forward
 * inference from C with no Python interpreter: every layer executes
 * through MXImperativeInvoke on the native dependency engine, using the
 * deployment op set registered in ndarray.cc (dense / conv2d /
 * batchnorm_inf / pooling / activations / flatten / softmax).
 */
#include <cctype>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

#include "./capi_common.h"
#include "./json.h"
#include "./mxtpu.h"

namespace {

using mxtpu::JValue;
using mxtpu::JParser;
using mxtpu::ReadFile;

/* ---- predictor ------------------------------------------------------- */

struct Node {
  std::string op;               /* deploy_graph op name */
  std::string weight, bias, gamma, beta, mean, var;
  std::string activation, act;
  int flatten = 0, global_pool = 0, include_pad = 1;
  int axis = 1;                 /* concat */
  std::vector<int> in;          /* SSA input value ids (r4); empty =
                                 * consume the previous node's output
                                 * (pre-r4 sequential exports) */
  int64_t kernel[2] = {0, 0}, stride[2] = {1, 1}, pad[2] = {0, 0};
  float eps = 1e-5f;
};

struct Predictor {
  std::vector<Node> nodes;
  std::map<std::string, NDArrayHandle> params;
  std::vector<NDArrayHandle> owned;     /* params + helper arrays */
  NDArrayHandle input = nullptr;
  NDArrayHandle output = nullptr;       /* alias into temps */
  std::vector<NDArrayHandle> temps;

  ~Predictor() {
    FreeTemps();
    if (input) MXNDArrayFree(input);
    for (auto h : owned) MXNDArrayFree(h);
  }
  void FreeTemps() {
    for (auto h : temps) MXNDArrayFree(h);
    temps.clear();
    output = nullptr;
  }
};

std::string JStr(const JValue *v, const char *what) {
  if (v == nullptr || v->kind == JValue::NUL) return "";
  if (v->kind != JValue::STR)
    throw std::runtime_error(std::string(what) + ": expected string");
  return v->str;
}

void JInt2(const JValue *v, int64_t out[2], const char *what) {
  if (v == nullptr || v->kind != JValue::ARR || v->arr.size() != 2)
    throw std::runtime_error(std::string(what) + ": expected [a, b]");
  out[0] = static_cast<int64_t>(v->arr[0].num);
  out[1] = static_cast<int64_t>(v->arr[1].num);
}

NDArrayHandle MakeArray(const std::vector<int64_t> &shape, int dtype) {
  NDArrayHandle h = nullptr;
  if (MXNDArrayCreate(shape.data(), static_cast<int>(shape.size()), dtype,
                      &h) != 0)
    throw std::runtime_error(MXGetLastError());
  return h;
}

/* helper arrays live in `temps` — freed at the start of every Forward,
 * so a long-running inference loop does not accumulate allocations */
NDArrayHandle IntAttrArray(Predictor *p, std::vector<int32_t> vals) {
  NDArrayHandle h = MakeArray({static_cast<int64_t>(vals.size())}, 4);
  if (MXNDArraySyncCopyFromCPU(h, vals.data(),
                               vals.size() * sizeof(int32_t)) != 0)
    throw std::runtime_error(MXGetLastError());
  p->temps.push_back(h);
  return h;
}

NDArrayHandle ZeroBias(Predictor *p, int64_t n) {
  NDArrayHandle h = MakeArray({n}, 0);
  std::vector<float> z(static_cast<size_t>(n), 0.f);
  if (MXNDArraySyncCopyFromCPU(h, z.data(), z.size() * sizeof(float)) != 0)
    throw std::runtime_error(MXGetLastError());
  p->temps.push_back(h);
  return h;
}

std::vector<int64_t> ShapeOf(NDArrayHandle h) {
  int nd = 0;
  const int64_t *s = nullptr;
  if (MXNDArrayGetShape(h, &nd, &s) != 0)
    throw std::runtime_error(MXGetLastError());
  return std::vector<int64_t>(s, s + nd);
}

NDArrayHandle Param(Predictor *p, const std::string &name) {
  auto it = p->params.find(name);
  if (it == p->params.end())
    throw std::runtime_error("param '" + name + "' missing from file");
  return it->second;
}

void Invoke(const char *op, std::vector<NDArrayHandle> in,
            NDArrayHandle out) {
  if (MXImperativeInvoke(op, in.data(), static_cast<int>(in.size()),
                         &out, 1) != 0)
    throw std::runtime_error(MXGetLastError());
}

NDArrayHandle Temp(Predictor *p, const std::vector<int64_t> &shape) {
  NDArrayHandle h = MakeArray(shape, 0);
  p->temps.push_back(h);
  return h;
}

NDArrayHandle ApplyAct(Predictor *p, const std::string &act,
                       NDArrayHandle h) {
  if (act.empty()) return h;
  if (act != "relu" && act != "sigmoid" && act != "tanh")
    throw std::runtime_error("unsupported activation '" + act + "'");
  NDArrayHandle o = Temp(p, ShapeOf(h));
  Invoke(act.c_str(), {h}, o);
  return o;
}

NDArrayHandle RunNode(Predictor *p, const Node &n,
                      const std::vector<NDArrayHandle> &ins) {
  if (n.op == "add") {
    if (ins.size() != 2)
      throw std::runtime_error("add: expected 2 inputs");
    NDArrayHandle o = Temp(p, ShapeOf(ins[0]));
    Invoke("add", {ins[0], ins[1]}, o);
    return o;
  }
  if (n.op == "concat") {
    if (ins.size() < 2)
      throw std::runtime_error("concat: expected >=2 inputs");
    std::vector<int64_t> os = ShapeOf(ins[0]);
    if (n.axis < 0 || static_cast<size_t>(n.axis) >= os.size())
      throw std::runtime_error("concat: axis out of range");
    os[n.axis] = 0;
    for (NDArrayHandle h2 : ins) {
      std::vector<int64_t> s2 = ShapeOf(h2);
      if (s2.size() != os.size())
        throw std::runtime_error("concat: input rank mismatch");
      os[n.axis] += s2[n.axis];
    }
    NDArrayHandle at = IntAttrArray(p, {static_cast<int32_t>(n.axis)});
    NDArrayHandle o = Temp(p, os);
    std::vector<NDArrayHandle> args(ins);
    args.push_back(at);
    Invoke("concat", args, o);
    return o;
  }
  if (ins.size() != 1)
    throw std::runtime_error("node '" + n.op +
                             "': expected exactly 1 input");
  NDArrayHandle h = ins[0];
  std::vector<int64_t> s = ShapeOf(h);
  if (n.op == "dense") {
    if (n.flatten && s.size() != 2) {
      int64_t rest = 1;
      for (size_t i = 1; i < s.size(); ++i) rest *= s[i];
      NDArrayHandle flat = Temp(p, {s[0], rest});
      Invoke("flatten", {h}, flat);
      h = flat;
      s = {s[0], rest};
    }
    NDArrayHandle W = Param(p, n.weight);
    NDArrayHandle b = n.bias.empty() ? ZeroBias(p, ShapeOf(W)[0])
                                     : Param(p, n.bias);
    NDArrayHandle o = Temp(p, {s[0], ShapeOf(W)[0]});
    Invoke("dense", {h, W, b}, o);
    return ApplyAct(p, n.activation, o);
  }
  if (n.op == "conv2d") {
    NDArrayHandle W = Param(p, n.weight);
    std::vector<int64_t> ws = ShapeOf(W);
    NDArrayHandle b = n.bias.empty() ? ZeroBias(p, ws[0])
                                     : Param(p, n.bias);
    NDArrayHandle at = IntAttrArray(
        p, {static_cast<int32_t>(n.stride[0]),
            static_cast<int32_t>(n.stride[1]),
            static_cast<int32_t>(n.pad[0]),
            static_cast<int32_t>(n.pad[1])});
    int64_t OH = (s[2] + 2 * n.pad[0] - ws[2]) / n.stride[0] + 1;
    int64_t OW = (s[3] + 2 * n.pad[1] - ws[3]) / n.stride[1] + 1;
    NDArrayHandle o = Temp(p, {s[0], ws[0], OH, OW});
    Invoke("conv2d", {h, W, b, at}, o);
    return ApplyAct(p, n.activation, o);
  }
  if (n.op == "maxpool2d" || n.op == "avgpool2d") {
    int flags = (n.global_pool ? 1 : 0) | (n.include_pad ? 2 : 0);
    NDArrayHandle at = IntAttrArray(
        p, {static_cast<int32_t>(n.kernel[0]),
            static_cast<int32_t>(n.kernel[1]),
            static_cast<int32_t>(n.stride[0]),
            static_cast<int32_t>(n.stride[1]),
            static_cast<int32_t>(n.pad[0]),
            static_cast<int32_t>(n.pad[1]), flags});
    int64_t OH = 1, OW = 1;
    if (!n.global_pool) {
      OH = (s[2] + 2 * n.pad[0] - n.kernel[0]) / n.stride[0] + 1;
      OW = (s[3] + 2 * n.pad[1] - n.kernel[1]) / n.stride[1] + 1;
    }
    NDArrayHandle o = Temp(p, {s[0], s[1], OH, OW});
    Invoke(n.op.c_str(), {h, at}, o);
    return o;
  }
  if (n.op == "batchnorm") {
    NDArrayHandle eps = MakeArray({1}, 0);
    if (MXNDArraySyncCopyFromCPU(eps, &n.eps, sizeof(float)) != 0)
      throw std::runtime_error(MXGetLastError());
    p->temps.push_back(eps);
    NDArrayHandle o = Temp(p, s);
    Invoke("batchnorm_inf",
           {h, Param(p, n.gamma), Param(p, n.beta), Param(p, n.mean),
            Param(p, n.var), eps}, o);
    return o;
  }
  if (n.op == "activation") return ApplyAct(p, n.act, h);
  if (n.op == "flatten") {
    int64_t rest = 1;
    for (size_t i = 1; i < s.size(); ++i) rest *= s[i];
    NDArrayHandle o = Temp(p, {s[0], rest});
    Invoke("flatten", {h}, o);
    return o;
  }
  if (n.op == "softmax") {
    NDArrayHandle o = Temp(p, s);
    Invoke("softmax", {h}, o);
    return o;
  }
  throw std::runtime_error("deploy op '" + n.op + "' not supported");
}

}  // namespace

namespace mxtpu {

/* Shared with symbol.cc (MXPredCreateFromSymbol): build a Predictor from
 * an already-parsed export meta object. Throws on error. */
void *BuildPredictorFromMeta(const JValue &meta, const char *param_file,
                             const int64_t *input_shape, int input_ndim) {
  const JValue *graph = meta.get("deploy_graph");
  if (graph == nullptr || graph->kind != JValue::ARR)
    throw std::runtime_error(
        "this export has no native deploy_graph (the model contains "
        "layers outside the C-deployable set: dense/conv2d/batchnorm/"
        "pool2d/activation/flatten/dropout/add/concat) — run it via "
        "the Python/StableHLO path instead");

  auto pred = std::unique_ptr<Predictor>(new Predictor());
  for (const JValue &jn : graph->arr) {
    Node n;
    n.op = JStr(jn.get("op"), "op");
    n.weight = JStr(jn.get("weight"), "weight");
    n.bias = JStr(jn.get("bias"), "bias");
    n.gamma = JStr(jn.get("gamma"), "gamma");
    n.beta = JStr(jn.get("beta"), "beta");
    n.mean = JStr(jn.get("mean"), "mean");
    n.var = JStr(jn.get("var"), "var");
    n.activation = JStr(jn.get("activation"), "activation");
    n.act = JStr(jn.get("act"), "act");
    if (const JValue *v = jn.get("flatten"))
      n.flatten = static_cast<int>(v->num);
    if (const JValue *v = jn.get("global"))
      n.global_pool = static_cast<int>(v->num);
    if (const JValue *v = jn.get("count_include_pad"))
      n.include_pad = static_cast<int>(v->num);
    if (const JValue *v = jn.get("eps"))
      n.eps = static_cast<float>(v->num);
    if (const JValue *v = jn.get("axis"))
      n.axis = static_cast<int>(v->num);
    if (const JValue *v = jn.get("in")) {
      if (v->kind != JValue::ARR)
        throw std::runtime_error("node 'in': expected an array");
      for (const JValue &e : v->arr) {
        if (e.kind != JValue::NUM)
          throw std::runtime_error(
              "node 'in': expected value ids (numbers)");
        n.in.push_back(static_cast<int>(e.num));
      }
    }
    if (jn.get("kernel")) JInt2(jn.get("kernel"), n.kernel, "kernel");
    if (jn.get("stride")) JInt2(jn.get("stride"), n.stride, "stride");
    if (jn.get("pad")) JInt2(jn.get("pad"), n.pad, "pad");
    if (n.stride[0] <= 0 || n.stride[1] <= 0)
      throw std::runtime_error("node '" + n.op +
                               "': stride must be positive");
    pred->nodes.push_back(std::move(n));
  }

  int n_params = 0;
  NDArrayHandle *handles = nullptr;
  char **names = nullptr;
  if (MXNDArrayLoad(param_file, &n_params, &handles, &names) != 0)
    throw std::runtime_error(MXGetLastError());
  for (int i = 0; i < n_params; ++i) {
    pred->params[names[i]] = handles[i];
    pred->owned.push_back(handles[i]);
  }
  /* frees the name strings + container arrays; the NDArray handles were
   * copied above and are owned by the predictor now */
  MXNDArrayLoadFree(n_params, handles, names);

  pred->input = MakeArray(
      std::vector<int64_t>(input_shape, input_shape + input_ndim), 0);
  return pred.release();
}

}  /* namespace mxtpu */

extern "C" {

int MXPredCreate(const char *symbol_json_file, const char *param_file,
                 const int64_t *input_shape, int input_ndim,
                 PredictorHandle *out) {
  API_BEGIN();
  JValue meta = JParser(ReadFile(symbol_json_file)).parse();
  *out = mxtpu::BuildPredictorFromMeta(meta, param_file, input_shape,
                                       input_ndim);
  API_END();
}

int MXPredSetInput(PredictorHandle h, const float *data, uint64_t size) {
  API_BEGIN();
  auto *p = static_cast<Predictor *>(h);
  if (MXNDArraySyncCopyFromCPU(p->input, data, size * sizeof(float)) != 0)
    throw std::runtime_error(MXGetLastError());
  API_END();
}

int MXPredForward(PredictorHandle h) {
  API_BEGIN();
  auto *p = static_cast<Predictor *>(h);
  p->FreeTemps();
  /* SSA value table: values[0] = input, values[k+1] = node k's output.
   * Nodes without "in" chain off the latest value (legacy exports). */
  std::vector<NDArrayHandle> values;
  values.push_back(p->input);
  for (const Node &n : p->nodes) {
    std::vector<NDArrayHandle> ins;
    if (n.in.empty()) {
      ins.push_back(values.back());
    } else {
      for (int v : n.in) {
        if (v < 0 || static_cast<size_t>(v) >= values.size())
          throw std::runtime_error("node '" + n.op +
                                   "': input value out of range");
        ins.push_back(values[static_cast<size_t>(v)]);
      }
    }
    values.push_back(RunNode(p, n, ins));
  }
  NDArrayHandle cur = values.back();
  if (MXNDArrayWaitToRead(cur) != 0)
    throw std::runtime_error(MXGetLastError());
  p->output = cur;
  API_END();
}

int MXPredGetOutputShape(PredictorHandle h, int *out_ndim,
                         const int64_t **out_shape) {
  API_BEGIN();
  auto *p = static_cast<Predictor *>(h);
  if (p->output == nullptr)
    throw std::runtime_error("call MXPredForward first");
  if (MXNDArrayGetShape(p->output, out_ndim, out_shape) != 0)
    throw std::runtime_error(MXGetLastError());
  API_END();
}

int MXPredGetOutput(PredictorHandle h, float *data, uint64_t size) {
  API_BEGIN();
  auto *p = static_cast<Predictor *>(h);
  if (p->output == nullptr)
    throw std::runtime_error("call MXPredForward first");
  if (MXNDArraySyncCopyToCPU(p->output, data, size * sizeof(float)) != 0)
    throw std::runtime_error(MXGetLastError());
  API_END();
}

int MXPredFree(PredictorHandle h) {
  API_BEGIN();
  delete static_cast<Predictor *>(h);
  API_END();
}

}  /* extern "C" */
