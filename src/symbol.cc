/*
 * symbol.cc — C symbol surface (reference: src/c_api/c_api_symbolic.cc:
 * MXSymbolCreateFromFile/FromJSON, MXSymbolSaveToJSON,
 * MXSymbolListArguments, MXSymbolListAuxiliaryStates, MXSymbolListOutputs,
 * MXSymbolListAttr, MXSymbolFree).
 *
 * A Symbol here wraps the HybridBlock.export() artifact: the parsed meta
 * json (inputs / params / param_order / deploy_graph / StableHLO payload).
 * Argument vs auxiliary-state split follows the reference convention:
 * BatchNorm running statistics (``*running_mean`` / ``*running_var``) are
 * auxiliary states (not gradients targets); everything else in
 * ``param_order`` is an argument. ``MXPredCreateFromSymbol`` builds the
 * native predictor from an already-loaded symbol, completing the
 * symbol → executor C-side story for deployment.
 */
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

#include "./capi_common.h"
#include "./json.h"
#include "./mxtpu.h"

namespace mxtpu {
void *BuildPredictorFromMeta(const JValue &meta, const char *param_file,
                             const int64_t *input_shape, int input_ndim);
}

namespace {

using mxtpu::JValue;
using mxtpu::JParser;
using mxtpu::ReadFile;

bool IsAuxName(const std::string &name) {
  /* reference: aux_states = BN moving statistics (ndarray.h kAuxArg);
   * stat_shift is this framework's extra BN stability buffer — untrained
   * state, same class. Match the final dot-separated segment exactly so
   * a user layer merely NAMED e.g. "running_mean_head" keeps its weights
   * in the argument list. */
  size_t dot = name.rfind('.');
  std::string last = dot == std::string::npos ? name : name.substr(dot + 1);
  return last == "running_mean" || last == "running_var" ||
         last == "stat_shift";
}

struct Symbol {
  std::string json;                       /* raw text (SaveToJSON) */
  JValue meta;
  std::vector<std::string> args, aux, outputs, ops;
  std::vector<const char *> args_c, aux_c, outputs_c, ops_c;
  std::vector<std::vector<int64_t>> input_shapes;
  std::vector<std::string> input_dtypes;
  std::map<std::string, std::string> attr_cache;  /* rendered GetAttr values */

  void Index() {
    const JValue *order = meta.get("param_order");
    if (order != nullptr && order->kind == JValue::ARR) {
      for (const JValue &v : order->arr) {
        if (v.kind != JValue::STR)
          throw std::runtime_error("param_order: expected strings");
        (IsAuxName(v.str) ? aux : args).push_back(v.str);
      }
    }
    const JValue *blk = meta.get("block");
    std::string base =
        (blk != nullptr && blk->kind == JValue::STR) ? blk->str : "symbol";
    for (char &c : base)
      c = static_cast<char>(std::tolower(static_cast<unsigned char>(c)));
    outputs.push_back(base + "_output");   /* reference "<name>_output" */
    const JValue *graph = meta.get("deploy_graph");
    if (graph != nullptr && graph->kind == JValue::ARR) {
      for (const JValue &node : graph->arr) {
        const JValue *op = node.get("op");
        if (op != nullptr && op->kind == JValue::STR)
          ops.push_back(op->str);
      }
    }
    const JValue *inputs = meta.get("inputs");
    if (inputs != nullptr && inputs->kind == JValue::ARR) {
      for (const JValue &in : inputs->arr) {
        std::vector<int64_t> shape;
        const JValue *js = in.get("shape");
        if (js != nullptr && js->kind == JValue::ARR)
          for (const JValue &d : js->arr)
            shape.push_back(static_cast<int64_t>(d.num));
        input_shapes.push_back(std::move(shape));
        const JValue *jd = in.get("dtype");
        input_dtypes.push_back(
            (jd != nullptr && jd->kind == JValue::STR) ? jd->str : "");
      }
    }
    for (const auto &s : args) args_c.push_back(s.c_str());
    for (const auto &s : aux) aux_c.push_back(s.c_str());
    for (const auto &s : outputs) outputs_c.push_back(s.c_str());
    for (const auto &s : ops) ops_c.push_back(s.c_str());
  }
};

Symbol *Sym(SymbolHandle h) {
  if (h == nullptr) throw std::runtime_error("null SymbolHandle");
  return static_cast<Symbol *>(h);
}

SymbolHandle CreateFromText(std::string text) {
  auto sym = std::unique_ptr<Symbol>(new Symbol());
  sym->json = std::move(text);
  sym->meta = JParser(sym->json).parse();
  if (sym->meta.kind != JValue::OBJ)
    throw std::runtime_error("symbol json: expected a top-level object");
  sym->Index();
  return sym.release();
}

}  // namespace

extern "C" {

int MXSymbolCreateFromFile(const char *path, SymbolHandle *out) {
  API_BEGIN();
  *out = CreateFromText(ReadFile(path));
  API_END();
}

int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  API_BEGIN();
  if (json == nullptr) throw std::runtime_error("null json");
  *out = CreateFromText(std::string(json));
  API_END();
}

int MXSymbolSaveToJSON(SymbolHandle h, char **out_json) {
  API_BEGIN();
  Symbol *s = Sym(h);
  char *buf = static_cast<char *>(std::malloc(s->json.size() + 1));
  if (buf == nullptr) throw std::runtime_error("out of memory");
  std::memcpy(buf, s->json.data(), s->json.size());
  buf[s->json.size()] = '\0';
  *out_json = buf;                      /* free via MXFreeString */
  API_END();
}

int MXSymbolSaveToFile(SymbolHandle h, const char *path) {
  API_BEGIN();
  Symbol *s = Sym(h);
  std::ofstream f(path, std::ios::binary);
  if (!f) throw std::runtime_error(std::string("cannot open ") + path);
  f << s->json;
  if (!f) throw std::runtime_error(std::string("write failed: ") + path);
  API_END();
}

int MXSymbolListArguments(SymbolHandle h, int *out_n,
                          const char ***out_names) {
  API_BEGIN();
  Symbol *s = Sym(h);
  *out_n = static_cast<int>(s->args_c.size());
  *out_names = s->args_c.data();
  API_END();
}

int MXSymbolListAuxiliaryStates(SymbolHandle h, int *out_n,
                                const char ***out_names) {
  API_BEGIN();
  Symbol *s = Sym(h);
  *out_n = static_cast<int>(s->aux_c.size());
  *out_names = s->aux_c.data();
  API_END();
}

int MXSymbolListOutputs(SymbolHandle h, int *out_n,
                        const char ***out_names) {
  API_BEGIN();
  Symbol *s = Sym(h);
  *out_n = static_cast<int>(s->outputs_c.size());
  *out_names = s->outputs_c.data();
  API_END();
}

int MXSymbolListDeployOps(SymbolHandle h, int *out_n,
                          const char ***out_names) {
  API_BEGIN();
  Symbol *s = Sym(h);
  *out_n = static_cast<int>(s->ops_c.size());
  *out_names = s->ops_c.data();
  API_END();
}

int MXSymbolGetAttr(SymbolHandle h, const char *key, const char **out) {
  /* top-level scalar meta fields: "framework", "block",
   * "format_version", ... Returns success with *out = NULL when the key
   * is absent (reference MXSymbolGetAttr contract). */
  API_BEGIN();
  Symbol *s = Sym(h);
  if (key == nullptr) throw std::runtime_error("null key");
  *out = nullptr;
  const JValue *v = s->meta.get(key);
  if (v == nullptr) return 0;
  /* rendered once per key, stored on the symbol: pointers stay valid
   * until MXSymbolFree and die with it */
  auto it = s->attr_cache.find(key);
  if (it != s->attr_cache.end()) {
    *out = it->second.c_str();
    return 0;
  }
  std::string text;
  switch (v->kind) {
    case JValue::STR: text = v->str; break;
    case JValue::NUM: {
      std::ostringstream ss;
      if (v->num == static_cast<int64_t>(v->num))
        ss << static_cast<int64_t>(v->num);
      else
        ss << v->num;
      text = ss.str();
      break;
    }
    case JValue::BOOL: text = v->b ? "true" : "false"; break;
    default: return 0;                  /* arrays/objects: not an attr */
  }
  auto &slot = s->attr_cache[key];
  slot = std::move(text);
  *out = slot.c_str();
  API_END();
}

int MXSymbolGetNumInputs(SymbolHandle h, int *out_n) {
  API_BEGIN();
  *out_n = static_cast<int>(Sym(h)->input_shapes.size());
  API_END();
}

int MXSymbolGetInputShape(SymbolHandle h, int index, int *out_ndim,
                          const int64_t **out_shape,
                          const char **out_dtype) {
  API_BEGIN();
  Symbol *s = Sym(h);
  if (index < 0 || index >= static_cast<int>(s->input_shapes.size()))
    throw std::runtime_error("input index out of range");
  *out_ndim = static_cast<int>(s->input_shapes[index].size());
  *out_shape = s->input_shapes[index].data();
  *out_dtype = s->input_dtypes[index].c_str();
  API_END();
}

int MXSymbolFree(SymbolHandle h) {
  API_BEGIN();
  delete static_cast<Symbol *>(h);
  API_END();
}

int MXPredCreateFromSymbol(SymbolHandle sym, const char *param_file,
                           const int64_t *input_shape, int input_ndim,
                           PredictorHandle *out) {
  API_BEGIN();
  Symbol *s = Sym(sym);
  *out = mxtpu::BuildPredictorFromMeta(s->meta, param_file, input_shape,
                                       input_ndim);
  API_END();
}

}  /* extern "C" */
