/*
 * mxtpu.h — C API for the native runtime of mxnet_tpu.
 *
 * Reference parity (leezu/mxnet): include/mxnet/c_api.h (error trampoline,
 * handle-based API), include/mxnet/engine.h (Engine::PushAsync var
 * semantics), include/mxnet/storage.h (pooled allocator),
 * 3rdparty/dmlc-core/include/dmlc/recordio.h (record framing).
 *
 * The compute path of mxnet_tpu is JAX/XLA/Pallas; this library is the
 * native runtime *around* it: an asynchronous dependency engine for host
 * work (IO decode, custom ops, checkpoint writes), a pooled host allocator
 * for staging buffers, and the RecordIO data plane with a threaded
 * prefetcher.  Every function returns 0 on success, -1 on failure with the
 * message retrievable via MXGetLastError() (thread-local), matching the
 * reference's MXGetLastError contract.
 */
#ifndef MXTPU_H_
#define MXTPU_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void *EngineHandle;
typedef void *EngineVarHandle;
typedef void *RecordIOHandle;
typedef void *PrefetcherHandle;

/* Callback executed by an engine worker thread.  `ctx` is the opaque
 * pointer given to MXEnginePushAsync. */
typedef void (*MXEngineFn)(void *ctx);
/* Called exactly once after the op's fn has run (or been cancelled on
 * engine shutdown, in which case `cancelled` is 1). */
typedef void (*MXEngineOnComplete)(void *ctx, int cancelled);

/* ---- error handling (c_api_error.cc analog) ---- */
const char *MXGetLastError(void);

/* ---- dependency engine (threaded_engine_perdevice.cc analog) ---- */
/* num_workers<=0 picks hardware_concurrency.  naive!=0 => every push runs
 * synchronously on the calling thread (MXNET_ENGINE_TYPE=NaiveEngine). */
int MXEngineCreate(int num_workers, int naive, EngineHandle *out);
int MXEngineFree(EngineHandle h);
int MXEngineNewVar(EngineHandle h, EngineVarHandle *out);
/* Deletes the var once all pending ops touching it have completed. */
int MXEngineFreeVar(EngineHandle h, EngineVarHandle var);
/* Push fn with read/write dependencies.  A var listed in both sets is
 * treated as write.  `name` may be NULL; it labels profiler events.
 * on_complete may be NULL.  priority>0 jumps the dispatch queue. */
int MXEnginePushAsync(EngineHandle h, MXEngineFn fn, void *ctx,
                      MXEngineOnComplete on_complete,
                      EngineVarHandle *read_vars, int n_read,
                      EngineVarHandle *write_vars, int n_write,
                      int priority, const char *name);
int MXEngineWaitForVar(EngineHandle h, EngineVarHandle var);
int MXEngineWaitAll(EngineHandle h);
/* Profiling: when enabled the engine records one event per executed op. */
int MXEngineSetProfiling(EngineHandle h, int enabled);
/* Returns a malloc'd JSON array of chrome-trace event objects (caller
 * frees with MXFreeString) and clears the buffer. */
int MXEngineDumpProfile(EngineHandle h, char **out_json);
int MXFreeString(char *s);

/* ---- pooled storage manager (storage/pooled_storage_manager.h analog) */
int MXStorageAlloc(size_t size, void **out);
int MXStorageFree(void *ptr);
/* Drop all cached free blocks back to the OS. */
int MXStorageReleaseAll(void);
int MXStorageStats(uint64_t *bytes_in_use, uint64_t *bytes_pooled,
                   uint64_t *pool_hits, uint64_t *pool_misses);

/* ---- RecordIO (dmlc/recordio.h analog; format-compatible) ---- */
int MXRecordIOWriterCreate(const char *path, RecordIOHandle *out);
/* Writes one framed record; *out_pos receives its byte offset. */
int MXRecordIOWriterWrite(RecordIOHandle h, const char *data, uint64_t size,
                          uint64_t *out_pos);
int MXRecordIOWriterTell(RecordIOHandle h, uint64_t *out_pos);
int MXRecordIOWriterFree(RecordIOHandle h);

int MXRecordIOReaderCreate(const char *path, RecordIOHandle *out);
/* *out_data points at an internal buffer valid until the next call.
 * At EOF returns 0 with *out_data = NULL. */
int MXRecordIOReaderNext(RecordIOHandle h, const char **out_data,
                         uint64_t *out_size);
int MXRecordIOReaderSeek(RecordIOHandle h, uint64_t pos);
int MXRecordIOReaderTell(RecordIOHandle h, uint64_t *out_pos);
/* Scans the whole file and returns a malloc'd array of record offsets
 * (caller frees with MXFreeBuffer); leaves the read position at 0. */
int MXRecordIOReaderScanIndex(RecordIOHandle h, uint64_t **out_positions,
                              uint64_t *out_count);
int MXRecordIOReaderFree(RecordIOHandle h);
int MXFreeBuffer(void *buf);

/* ---- threaded record prefetcher (iter_prefetcher.h analog) ----
 * A background thread reads records (optionally following a shuffled /
 * sharded index) into a bounded queue of batches backed by the pooled
 * allocator. */
int MXPrefetcherCreate(const char *path, int batch_size, int capacity,
                       const uint64_t *index, uint64_t index_len,
                       PrefetcherHandle *out);
/* Blocks for the next batch.  Fills caller arrays data[i]/sizes[i]
 * (capacity batch_size); *out_n receives the number of records (0 at
 * epoch end).  Buffers stay valid until the following Next/Free. */
int MXPrefetcherNext(PrefetcherHandle h, const char **data, uint64_t *sizes,
                     int *out_n);
int MXPrefetcherReset(PrefetcherHandle h);
int MXPrefetcherFree(PrefetcherHandle h);

/* ---- NDArray C surface (c_api_ndarray.cc analog) ----
 * Host tensors over the pooled allocator with engine-scheduled native
 * ops: the deployment/runtime half of the ABI.  The accelerator op set
 * (445 ops) lives behind the Python/XLA path by design (SURVEY.md L4
 * stance: ONE clean C API + Python frontend); the ops listed here are
 * the native-runtime kernels executable without a Python interpreter. */
typedef void *NDArrayHandle;

/* dtypes: 0=float32 1=float64 3=uint8 4=int32 6=int64 12=bfloat16
 * (reference mshadow type codes). */
int MXNDArrayCreate(const int64_t *shape, int ndim, int dtype,
                    NDArrayHandle *out);
int MXNDArrayFree(NDArrayHandle h);
int MXNDArrayGetShape(NDArrayHandle h, int *out_ndim,
                      const int64_t **out_shape);
int MXNDArrayGetDType(NDArrayHandle h, int *out_dtype);
int MXNDArraySize(NDArrayHandle h, uint64_t *out_size);
/* Blocks until pending engine ops writing this array finish. */
int MXNDArrayWaitToRead(NDArrayHandle h);
int MXNDArrayWaitAll(void);
/* Raw data pointer (host); call MXNDArrayWaitToRead first. */
int MXNDArrayGetData(NDArrayHandle h, void **out);
int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                             uint64_t nbytes);
int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *data, uint64_t nbytes);

/* Invoke a registered native op asynchronously through the dependency
 * engine (Imperative::Invoke -> PushFCompute analog).  Outputs must be
 * pre-created with the correct shape/dtype.  Same-shape elementwise:
 * add, sub, mul, div, relu, exp; matrix: dot (2-D f32); reduction:
 * sum (scalar out); copy. */
int MXImperativeInvoke(const char *op_name,
                       NDArrayHandle *inputs, int n_in,
                       NDArrayHandle *outputs, int n_out);
/* Native-runtime op names; pointers are static storage. */
int MXListAllOpNames(int *out_n, const char ***out_names);

/* ---- .params serialization (NDArray::Save/Load analog) ----
 * Byte-compatible with mxnet_tpu/ndarray_io.py (MXTPU001 container). */
int MXNDArraySave(const char *fname, int num, NDArrayHandle *handles,
                  const char **names);
/* Caller frees handles with MXNDArrayFree and the arrays with
 * MXNDArrayLoadFree. */
int MXNDArrayLoad(const char *fname, int *out_num,
                  NDArrayHandle **out_handles, char ***out_names);
int MXNDArrayLoadFree(int num, NDArrayHandle *handles, char **names);

/* ---- C predict API (c_predict_api.cc analog) ----
 * Runs a HybridBlock.export()ed model from C with no Python: parses the
 * symbol json's "deploy_graph" layer-op list (emitted when the model is
 * composed of natively-deployable layers: dense / conv2d / batchnorm /
 * pool2d / activation / flatten / dropout) and executes it through
 * MXImperativeInvoke on the dependency engine, with weights from the
 * .params file.  float32 inputs/outputs. */
typedef void *PredictorHandle;

int MXPredCreate(const char *symbol_json_file, const char *param_file,
                 const int64_t *input_shape, int input_ndim,
                 PredictorHandle *out);
int MXPredSetInput(PredictorHandle h, const float *data, uint64_t size);
int MXPredForward(PredictorHandle h);
/* Shape pointer valid until the next Forward/Free. */
int MXPredGetOutputShape(PredictorHandle h, int *out_ndim,
                         const int64_t **out_shape);
int MXPredGetOutput(PredictorHandle h, float *data, uint64_t size);
int MXPredFree(PredictorHandle h);

/* ---- C symbol API (c_api_symbolic.cc analog) ----
 * A Symbol wraps the export() artifact (the "-symbol.json" meta: inputs,
 * param_order, deploy_graph, StableHLO payload). Name lists returned by
 * the List* functions are owned by the symbol and stay valid until
 * MXSymbolFree. Argument/auxiliary split follows the reference: BN
 * running statistics are auxiliary states, everything else arguments. */
typedef void *SymbolHandle;

int MXSymbolCreateFromFile(const char *path, SymbolHandle *out);
int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
/* Returns the json text; free via MXFreeString. */
int MXSymbolSaveToJSON(SymbolHandle h, char **out_json);
int MXSymbolSaveToFile(SymbolHandle h, const char *path);
int MXSymbolListArguments(SymbolHandle h, int *out_n,
                          const char ***out_names);
int MXSymbolListAuxiliaryStates(SymbolHandle h, int *out_n,
                                const char ***out_names);
int MXSymbolListOutputs(SymbolHandle h, int *out_n,
                        const char ***out_names);
/* Op names of the native deploy_graph (empty when the export has none). */
int MXSymbolListDeployOps(SymbolHandle h, int *out_n,
                          const char ***out_names);
/* Top-level scalar meta fields ("framework", "block", "format_version");
 * success with *out = NULL when absent. */
int MXSymbolGetAttr(SymbolHandle h, const char *key, const char **out);
int MXSymbolGetNumInputs(SymbolHandle h, int *out_n);
int MXSymbolGetInputShape(SymbolHandle h, int index, int *out_ndim,
                          const int64_t **out_shape,
                          const char **out_dtype);
int MXSymbolFree(SymbolHandle h);
/* Build the native predictor from an already-loaded symbol. */
int MXPredCreateFromSymbol(SymbolHandle sym, const char *param_file,
                           const int64_t *input_shape, int input_ndim,
                           PredictorHandle *out);

/* ---- runtime feature introspection (libinfo.cc analog) ---- */
const char *MXLibInfoFeatures(void);

#ifdef __cplusplus
}
#endif

#endif /* MXTPU_H_ */
