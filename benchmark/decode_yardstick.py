#!/usr/bin/env python
"""KV-cache decode yardstick: our GPT decode loop vs HuggingFace
transformers (torch) on the SAME host CPU.

BASELINE config 8 records on-chip decode throughput with no comparison
point (VERDICT r3 weak 8). The reference framework has no decode path,
so the yardstick is the de-facto standard stack: HF ``generate()`` with
``use_cache=True`` on torch-CPU, vs ``GPTModel.generate()`` on XLA-CPU,
identical architecture (GPT-2-124M), batch, prompt, and new-token
counts, both greedy. Random weights — decode cost is weight-value
independent (and the image has no network for checkpoint downloads;
logit-level parity with real GPT-2 weights is separately proven in
tests/test_hf.py via contrib.hf conversion).

    python benchmark/decode_yardstick.py [--batch 8] [--new 128]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_ours(batch, prompt_len, new_tokens, repeats=3):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTModel

    mx.random.seed(0)
    net = GPTModel(vocab_size=50257, num_layers=12, units=768,
                   hidden_size=3072, num_heads=12, max_length=1024,
                   dropout=0.0)
    net.initialize()
    toks = onp.random.RandomState(0).randint(
        0, 50257, (batch, prompt_len)).astype("int32")
    net.generate(toks, new_tokens)              # compile, off the clock
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = net.generate(toks, new_tokens)
        out.asnumpy()
        best = min(best, time.perf_counter() - t0)
    return batch * new_tokens / best


def bench_hf(batch, prompt_len, new_tokens, repeats=3):
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    cfg = GPT2Config(n_layer=12, n_embd=768, n_head=12,
                     n_positions=1024, vocab_size=50257)
    model = GPT2LMHeadModel(cfg).eval()
    toks = torch.randint(0, 50257, (batch, prompt_len))
    with torch.no_grad():
        model.generate(toks, max_new_tokens=8, do_sample=False,
                       use_cache=True)          # warm caches/allocs
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            model.generate(toks, max_new_tokens=new_tokens,
                           min_new_tokens=new_tokens,
                           do_sample=False, use_cache=True,
                           pad_token_id=0)
            best = min(best, time.perf_counter() - t0)
    return batch * new_tokens / best


def bench_ours_chip(batch, prompt_len, new_tokens, dtype, repeats=3):
    """Our decode loop on the REAL chip (no platform override)."""
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTModel

    mx.random.seed(0)
    net = GPTModel(vocab_size=50257, num_layers=12, units=768,
                   hidden_size=3072, num_heads=12, max_length=1024,
                   dropout=0.0)
    net.initialize()
    net(mx.np.zeros((1, 8), dtype="int32"))
    if dtype != "float32":
        net.cast(dtype)
    toks = onp.random.RandomState(0).randint(
        0, 50257, (batch, prompt_len)).astype("int32")
    net.generate(toks, new_tokens)              # compile, off the clock
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = net.generate(toks, new_tokens)
        out.asnumpy()
        best = min(best, time.perf_counter() - t0)
    return batch * new_tokens / best


def bench_rawjax_chip(batch, prompt_len, new_tokens, dtype, repeats=3):
    """Hand-rolled raw-jax GPT-2 KV-cache decode on the SAME chip — the
    'what can jax alone do' comparison row for BASELINE config 8
    (VERDICT r4 weak 6).  Identical arch (12L/768/12H, tied head),
    identical structure to our product loop: one jitted prefill, one
    jitted lax.scan over the new tokens, static (max_length) cache
    shapes, greedy argmax.  Weights random (decode cost is
    value-independent)."""
    import jax
    import jax.numpy as jnp
    import numpy as onp
    from jax import lax

    L, C, H, V, MAXLEN = 12, 768, 12, 50257, 1024
    D = C // H
    dt = jnp.dtype(dtype)
    rng = onp.random.RandomState(0)

    def mkw(*shape, s=0.02):
        return jnp.asarray(rng.normal(0, s, shape).astype("float32"), dt)

    params = {
        "wte": mkw(V, C), "wpe": mkw(MAXLEN, C),
        "blocks": [{
            "ln1_g": jnp.ones((C,), dt), "ln1_b": jnp.zeros((C,), dt),
            "qkv_w": mkw(C, 3 * C), "qkv_b": jnp.zeros((3 * C,), dt),
            "out_w": mkw(C, C), "out_b": jnp.zeros((C,), dt),
            "ln2_g": jnp.ones((C,), dt), "ln2_b": jnp.zeros((C,), dt),
            "fc_w": mkw(C, 4 * C), "fc_b": jnp.zeros((4 * C,), dt),
            "pr_w": mkw(4 * C, C), "pr_b": jnp.zeros((C,), dt),
        } for _ in range(L)],
        "lnf_g": jnp.ones((C,), dt), "lnf_b": jnp.zeros((C,), dt),
    }
    params = jax.device_put(params)

    def ln(x, g, b):
        xf = x.astype(jnp.float32)
        m = xf.mean(-1, keepdims=True)
        v = xf.var(-1, keepdims=True)
        return ((xf - m) * lax.rsqrt(v + 1e-5) * g.astype(jnp.float32)
                + b.astype(jnp.float32)).astype(x.dtype)

    def block(p, x, k_cache, v_cache, pos, T):
        # x (B, T, C); caches (B, H, MAXLEN, D); pos = write offset
        h = ln(x, p["ln1_g"], p["ln1_b"])
        qkv = h @ p["qkv_w"] + p["qkv_b"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        B = x.shape[0]
        q = q.reshape(B, T, H, D).transpose(0, 2, 1, 3)
        k = k.reshape(B, T, H, D).transpose(0, 2, 1, 3)
        v = v.reshape(B, T, H, D).transpose(0, 2, 1, 3)
        k_cache = lax.dynamic_update_slice(k_cache, k, (0, 0, pos, 0))
        v_cache = lax.dynamic_update_slice(v_cache, v, (0, 0, pos, 0))
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k_cache.astype(jnp.float32)) / (D ** 0.5)
        idx = jnp.arange(MAXLEN)[None, :]
        qpos = pos + jnp.arange(T)[:, None]
        s = jnp.where(idx <= qpos, s, -1e30)
        w = jax.nn.softmax(s, axis=-1)
        a = jnp.einsum("bhqk,bhkd->bhqd", w,
                       v_cache.astype(jnp.float32)).astype(x.dtype)
        a = a.transpose(0, 2, 1, 3).reshape(B, T, C)
        x = x + a @ p["out_w"] + p["out_b"]
        h2 = ln(x, p["ln2_g"], p["ln2_b"])
        x = x + jax.nn.gelu(h2 @ p["fc_w"] + p["fc_b"]) \
            @ p["pr_w"] + p["pr_b"]
        return x, k_cache, v_cache

    def fwd(params, toks, kc, vc, pos, T):
        x = (params["wte"][toks]
             + lax.dynamic_slice_in_dim(params["wpe"], pos, T)[None])
        for li, p in enumerate(params["blocks"]):
            x, kc_l, vc_l = block(p, x, kc[li], vc[li], pos, T)
            kc = kc.at[li].set(kc_l)
            vc = vc.at[li].set(vc_l)
        x = ln(x, params["lnf_g"], params["lnf_b"])
        logits = x[:, -1].astype(jnp.float32) \
            @ params["wte"].T.astype(jnp.float32)
        return logits, kc, vc

    @jax.jit
    def generate(params, toks):
        B = toks.shape[0]
        kc = jnp.zeros((L, B, H, MAXLEN, D), dt)
        vc = jnp.zeros((L, B, H, MAXLEN, D), dt)
        logits, kc, vc = fwd(params, toks, kc, vc, 0, prompt_len)
        nxt = jnp.argmax(logits, -1).astype(jnp.int32)

        def step(carry, i):
            nxt, kc, vc = carry
            logits, kc, vc = fwd(params, nxt[:, None], kc, vc,
                                 prompt_len + i, 1)
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            return (nxt, kc, vc), nxt

        (_, _, _), outs = lax.scan(
            step, (nxt, kc, vc), jnp.arange(new_tokens - 1))
        return jnp.concatenate([nxt[:, None], outs.T], axis=1)

    toks = jax.device_put(jnp.asarray(rng.randint(
        0, V, (batch, prompt_len)).astype("int32")))
    onp.asarray(generate(params, toks))        # compile, off the clock
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = generate(params, toks)
        onp.asarray(out)
        best = min(best, time.perf_counter() - t0)
    return batch * new_tokens / best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt", type=int, default=64)
    ap.add_argument("--new", type=int, default=128)
    ap.add_argument("--skip-hf", action="store_true")
    ap.add_argument("--chip", action="store_true",
                    help="same-chip ours-vs-raw-jax comparison "
                         "(BASELINE config 8 r5 row)")
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    if args.chip:
        ours = bench_ours_chip(args.batch, args.prompt, args.new,
                               args.dtype)
        print(f"ours  (chip, GPT-2-124M {args.dtype} b{args.batch} "
              f"p{args.prompt}+{args.new}): {ours:,.0f} tok/s")
        raw = bench_rawjax_chip(args.batch, args.prompt, args.new,
                                args.dtype)
        print(f"raw-jax (same chip, same arch/loop):     {raw:,.0f} tok/s")
        print(f"ratio ours/raw-jax: {ours / raw:.2f}x")
        return

    ours = bench_ours(args.batch, args.prompt, args.new)
    print(f"ours  (XLA-CPU, GPT-2-124M b{args.batch} "
          f"p{args.prompt}+{args.new}): {ours:,.0f} tok/s")
    if not args.skip_hf:
        hf = bench_hf(args.batch, args.prompt, args.new)
        print(f"HF    (torch-CPU, same config):           {hf:,.0f} tok/s")
        print(f"ratio ours/HF: {ours / hf:.2f}x")


if __name__ == "__main__":
    main()
