#!/usr/bin/env python
"""KV-cache decode yardstick: our GPT decode loop vs HuggingFace
transformers (torch) on the SAME host CPU.

BASELINE config 8 records on-chip decode throughput with no comparison
point (VERDICT r3 weak 8). The reference framework has no decode path,
so the yardstick is the de-facto standard stack: HF ``generate()`` with
``use_cache=True`` on torch-CPU, vs ``GPTModel.generate()`` on XLA-CPU,
identical architecture (GPT-2-124M), batch, prompt, and new-token
counts, both greedy. Random weights — decode cost is weight-value
independent (and the image has no network for checkpoint downloads;
logit-level parity with real GPT-2 weights is separately proven in
tests/test_hf.py via contrib.hf conversion).

    python benchmark/decode_yardstick.py [--batch 8] [--new 128]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def bench_ours(batch, prompt_len, new_tokens, repeats=3):
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTModel

    mx.random.seed(0)
    net = GPTModel(vocab_size=50257, num_layers=12, units=768,
                   hidden_size=3072, num_heads=12, max_length=1024,
                   dropout=0.0)
    net.initialize()
    toks = onp.random.RandomState(0).randint(
        0, 50257, (batch, prompt_len)).astype("int32")
    net.generate(toks, new_tokens)              # compile, off the clock
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = net.generate(toks, new_tokens)
        out.asnumpy()
        best = min(best, time.perf_counter() - t0)
    return batch * new_tokens / best


def bench_hf(batch, prompt_len, new_tokens, repeats=3):
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    cfg = GPT2Config(n_layer=12, n_embd=768, n_head=12,
                     n_positions=1024, vocab_size=50257)
    model = GPT2LMHeadModel(cfg).eval()
    toks = torch.randint(0, 50257, (batch, prompt_len))
    with torch.no_grad():
        model.generate(toks, max_new_tokens=8, do_sample=False,
                       use_cache=True)          # warm caches/allocs
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            model.generate(toks, max_new_tokens=new_tokens,
                           min_new_tokens=new_tokens,
                           do_sample=False, use_cache=True,
                           pad_token_id=0)
            best = min(best, time.perf_counter() - t0)
    return batch * new_tokens / best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--prompt", type=int, default=64)
    ap.add_argument("--new", type=int, default=128)
    ap.add_argument("--skip-hf", action="store_true")
    args = ap.parse_args()

    ours = bench_ours(args.batch, args.prompt, args.new)
    print(f"ours  (XLA-CPU, GPT-2-124M b{args.batch} "
          f"p{args.prompt}+{args.new}): {ours:,.0f} tok/s")
    if not args.skip_hf:
        hf = bench_hf(args.batch, args.prompt, args.new)
        print(f"HF    (torch-CPU, same config):           {hf:,.0f} tok/s")
        print(f"ratio ours/HF: {ours / hf:.2f}x")


if __name__ == "__main__":
    main()
