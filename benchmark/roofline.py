#!/usr/bin/env python
"""Chip roofline: measured compute ceilings for the shapes our models
actually run (VERDICT r3 weak 1 — the published BERT "effective
TFLOP/s" exceeded the single measured 8192^3 matmul rate, so one of the
two numbers was untrustworthy; this sweep replaces both).

Measurement method (per BASELINE's tunnel rules, plus one new trick):
each probe is ONE jitted program that runs the op ``iters`` times in a
``lax.scan`` whose carry feeds the next iteration (data dependence
prevents XLA from hoisting or deduplicating the work), returning a
single f32 scalar (no output streaming). Two warmups absorb the
donation recompile; the timed number is the best of ``reps`` calls.
Per-call dispatch and tunnel RTT amortize over ``iters``, so op-level
rates resolve even through the ~120 ms round-trip.

    python benchmark/roofline.py            # full sweep on the chip
    python benchmark/roofline.py --quick    # subset

Prints a table + one JSON line; BASELINE.md's ceiling table is
generated from this.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax
import numpy as onp


_TARGET_SECONDS = 0.5            # per-call compute target at ~150 TF/s
_ASSUMED_TF = 150e12


def _pick_iters(flops_per_iter):
    return max(8, min(8192, int(_TARGET_SECONDS * _ASSUMED_TF
                                / flops_per_iter)))


def _rate(step, x0, weights, flops_per_iter, iters, reps=3):
    """TFLOP/s by TWO-POINT DIFFERENCE: time ONE compiled program (a
    dynamic-trip-count fori_loop over the chained op) at N and 2N
    iterations and divide the extra work by the extra time — the tunnel
    round-trip (~120 ms), dispatch, and output fetch are the same fixed
    cost in both, so they cancel instead of flooring the rate (the
    failure mode of timing one call: a 3 ms workload reads as 2 TFLOP/s
    through a 120 ms RTT). One program serves both points, so each
    shape pays one compile. ``weights`` ride as ARGUMENTS (device
    handles), never closure constants — a closed-over 8192^2 f32 array
    inlines 256 MB into the remote-compile request and trips the
    tunnel's body limit."""
    def run(a, n, *ws):
        c = lax.fori_loop(0, n, lambda _, c: step(c, *ws), a)
        return jnp.sum(c.astype(jnp.float32))

    prog = jax.jit(run)

    def best_time(n):
        n = jnp.int32(n)
        float(prog(x0, n, *weights))  # warmup (compile on first call)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(prog(x0, n, *weights))
            best = min(best, time.perf_counter() - t0)
        return best

    t1 = best_time(iters)
    t2 = best_time(2 * iters)
    dt = t2 - t1
    if dt <= 0:
        return float("nan")
    return flops_per_iter * iters / dt / 1e12


def _dev_normal(seed, shape, dtype, scale=1.0):
    """Probe inputs generated ON the device — host-side arrays would
    ship through the tunnel's compile/call requests (a 12288^2 f32
    operand exceeds its body limit)."""
    gen = jax.jit(lambda s: (jax.random.normal(
        jax.random.PRNGKey(s), shape, jnp.float32) * scale).astype(dtype))
    out = gen(jnp.int32(seed))
    out.block_until_ready()
    return out


def matmul_probe(m, n, k, dtype, reps=3):
    """Chained (m,k)@(k,n): the carry rides the (m,k) slot, so n==k is
    required for square chains; for rectangular shapes the output is
    projected back to (m,k) by a second matmul that is part of the
    measured FLOPs."""
    A = _dev_normal(0, (m, k), dtype)
    B = _dev_normal(1, (k, n), dtype, 0.01)
    square = (n == k)
    if square:
        def step(c, B):
            return jnp.matmul(c, B)
        weights = (B,)
        flops_per_iter = 2.0 * m * n * k
    else:
        C = _dev_normal(2, (n, k), dtype, 0.01)

        def step(c, B, C):
            h = jnp.matmul(c, B)          # (m,k)@(k,n)
            return jnp.matmul(h, C)       # (m,n)@(n,k) back to carry
        weights = (B, C)
        flops_per_iter = 2.0 * m * n * k * 2

    return _rate(step, A, weights, flops_per_iter,
                 _pick_iters(flops_per_iter), reps)


def conv_probe(batch, c, h, w, kh=3, kw=3, dtype=jnp.bfloat16, reps=3):
    """Chained stride-1 same-padding (c -> c) conv — the shape class
    carrying most ResNet FLOPs."""
    X = _dev_normal(0, (batch, c, h, w), dtype)
    W = _dev_normal(1, (c, c, kh, kw), dtype, 0.01)

    def step(x, W):
        y = lax.conv_general_dilated(
            x, W, window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return y * 0.1                # keep activations bounded

    flops = 2.0 * batch * c * c * kh * kw * h * w
    return _rate(step, X, (W,), flops, _pick_iters(flops), reps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    dev = jax.devices()[0]
    print(f"# roofline on {dev} ({dev.platform})", flush=True)
    results = {}

    # -- square matmul ceiling sweep ------------------------------------
    sizes = [2048, 4096] if args.quick else [1024, 2048, 4096, 8192]
    for s in sizes:
        for dt, name in ((jnp.bfloat16, "bf16"), (jnp.float32, "f32")):
            tf = matmul_probe(s, s, s, dt)
            results[f"matmul_{name}_{s}"] = round(tf, 1)
            print(f"matmul {name} {s}^3: {tf:8.1f} TFLOP/s", flush=True)

    # -- model-shaped matmuls -------------------------------------------
    # BERT-base b16 T512: tokens = 8192 rows
    model_shapes = [
        ("bert_mlp_in", 8192, 3072, 768),     # h -> 4h
        ("bert_mlp_out", 8192, 768, 3072),    # 4h -> h
        ("bert_qkv", 8192, 2304, 768),        # fused qkv
        ("bert_vocab", 8192, 30522, 768),     # masked-LM projection
        ("gpt_mlp_in", 8192, 3072, 768),      # b8 T1024 identical rows
        ("attn_scores", 512, 512, 64),        # per-head score block
    ]
    for name, m, n, k in model_shapes:
        if args.quick and name not in ("bert_mlp_in", "bert_vocab"):
            continue
        tf = matmul_probe(m, n, k, jnp.bfloat16)
        results[f"mm_{name}_bf16"] = round(tf, 1)
        print(f"matmul {name} ({m}x{n}x{k}) bf16: {tf:8.1f} TFLOP/s",
              flush=True)

    # -- ResNet conv shapes (b128, the headline config) -----------------
    conv_shapes = [
        ("conv_c64_56", 128, 64, 56, 56),
        ("conv_c128_28", 128, 128, 28, 28),
        ("conv_c256_14", 128, 256, 14, 14),
        ("conv_c512_7", 128, 512, 7, 7),
    ]
    for name, b, c, h, w in conv_shapes:
        if args.quick and name != "conv_c128_28":
            continue
        tf = conv_probe(b, c, h, w)
        results[name + "_bf16"] = round(tf, 1)
        print(f"{name} (b{b} {c}x{h}x{w} 3x3 s1): {tf:8.1f} TFLOP/s",
              flush=True)

    print(json.dumps({"roofline": results}), flush=True)


if __name__ == "__main__":
    main()
