"""INT8 post-training-quantized inference vs bf16 on the real chip.

Measures ResNet-50 b128 forward throughput for (a) the bf16 model and
(b) the same model through ``contrib.quantization.quantize_net`` (naive
calibration, one batch) — evidence for whether the v5e's int8 MXU rate
(2x bf16 nominal) survives the quantize/dequantize traffic XLA emits
around each int8 dot at inference batch sizes.

Timing per docs/performance.md rule 6 / the verify skill: host fetch
forces execution (axon results are lazy); whole-batch jit amortizes the
dispatch floor.

Usage: python benchmark/int8_infer_probe.py [batch]
"""
import sys
import time

import numpy as onp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import mxnet_tpu as mx  # noqa: E402


def timed(net, x, n=30):
    net(x).asnumpy()
    net(x).asnumpy()
    t0 = time.perf_counter()
    for _ in range(n):
        y = net(x)
    y.asnumpy()
    return (time.perf_counter() - t0) / n


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    rng = onp.random.RandomState(0)
    x_np = rng.uniform(-1, 1, (B, 3, 224, 224)).astype("float32")

    mx.random.seed(0)
    net = mx.gluon.model_zoo.vision.resnet50_v1()
    net.initialize()
    net(mx.np.zeros((1, 3, 64, 64)))      # settle shapes

    # bf16 arm
    net.cast("bfloat16")
    net.hybridize()
    x16 = mx.np.array(x_np.astype("bfloat16"))
    t_bf16 = timed(net, x16)
    print(f"bf16  fwd: {t_bf16 * 1e3:7.2f} ms/batch "
          f"({B / t_bf16:8.1f} img/s)", flush=True)

    # int8 arm: fresh float net, calibrate on one small batch, quantize
    mx.random.seed(0)
    qnet = mx.gluon.model_zoo.vision.resnet50_v1()
    qnet.initialize()
    qnet(mx.np.zeros((1, 3, 64, 64)))
    from mxnet_tpu.contrib.quantization import quantize_net
    calib = [(mx.np.array(x_np[:8]), None)]
    quantize_net(qnet, calib_data=calib, calib_mode="naive")
    qnet.hybridize()
    x32 = mx.np.array(x_np)
    t_int8 = timed(qnet, x32)
    print(f"int8  fwd: {t_int8 * 1e3:7.2f} ms/batch "
          f"({B / t_int8:8.1f} img/s)  ratio bf16/int8: "
          f"{t_bf16 / t_int8:4.2f}x", flush=True)


if __name__ == "__main__":
    main()
