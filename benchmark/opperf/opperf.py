#!/usr/bin/env python
"""Per-operator performance harness.

Reference parity (leezu/mxnet): ``benchmark/opperf/`` — runs every
registered operator with representative inputs under the profiler and
emits a JSON/markdown summary (count, mean/p50/p90 time).

Design (tpu-first): each op is timed two ways — eager dispatch (the
python→device hot path, reference's imperative overhead metric) and
jit-compiled steady state (what XLA makes of it) — on synthetic inputs
sized by ``--size``. Blocks on the result to exclude async-dispatch
illusions.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# (op name, builder) — representative input shapes per op family
def _default_cases(size):
    import numpy as onp
    import mxnet_tpu as mx
    rng = onp.random.RandomState(0)
    a = mx.np.array(rng.uniform(-1, 1, (size, size)).astype("float32"))
    b = mx.np.array(rng.uniform(-1, 1, (size, size)).astype("float32"))
    v = mx.np.array(rng.uniform(-1, 1, (size * size,)).astype("float32"))
    img = mx.np.array(rng.uniform(-1, 1, (8, 32, size // 4 or 1,
                                          size // 4 or 1))
                      .astype("float32"))
    w = mx.np.array(rng.uniform(-1, 1, (32, 32, 3, 3)).astype("float32"))
    idx = mx.np.array(rng.randint(0, size, (size,)).astype("int32"))
    emb = mx.np.array(rng.uniform(-1, 1, (size, 64)).astype("float32"))
    return {
        "add": lambda: a + b,
        "mul": lambda: a * b,
        "exp": lambda: mx.np.exp(a),
        "tanh": lambda: mx.np.tanh(a),
        "dot": lambda: mx.np.dot(a, b),
        "sum": lambda: a.sum(),
        "mean_axis": lambda: a.mean(axis=1),
        "transpose": lambda: a.T + 0,
        "reshape": lambda: v.reshape(size, size) + 0,
        "slice": lambda: a[: size // 2, : size // 2] + 0,
        "argsort": lambda: mx.np.argsort(v[:1024]),
        "softmax": lambda: mx.npx.softmax(a, axis=-1),
        "relu": lambda: mx.npx.relu(a),
        "layer_norm": lambda: mx.npx.layer_norm(
            a, mx.np.ones((size,)), mx.np.zeros((size,))),
        "fully_connected": lambda: mx.npx.fully_connected(
            a, b, num_hidden=size, no_bias=True),
        "convolution": lambda: mx.npx.convolution(
            img, w, kernel=(3, 3), pad=(1, 1), num_filter=32,
            no_bias=True),
        "embedding": lambda: mx.npx.embedding(idx, emb, size, 64),
        "take": lambda: mx.np.take(emb, idx, axis=0),
    }


def _block(out):
    if isinstance(out, (tuple, list)):
        for o in out:
            o.wait_to_read()
    else:
        out.wait_to_read()


def bench_op(fn, warmup, runs):
    for _ in range(warmup):
        _block(fn())
    times = []
    for _ in range(runs):
        t0 = time.perf_counter()
        _block(fn())
        times.append((time.perf_counter() - t0) * 1e6)
    times.sort()
    n = len(times)
    return {"mean_us": sum(times) / n, "p50_us": times[n // 2],
            "p90_us": times[int(n * 0.9)], "min_us": times[0]}


def run(size=256, warmup=5, runs=20, ops=None):
    cases = _default_cases(size)
    if ops:
        cases = {k: v for k, v in cases.items() if k in ops}
    results = {}
    for name, fn in cases.items():
        try:
            results[name] = bench_op(fn, warmup, runs)
        except Exception as e:      # record per-op failures, keep going
            results[name] = {"error": str(e)}
    return results


def main(argv=None):
    ap = argparse.ArgumentParser(description="per-op perf harness")
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--warmup", type=int, default=5)
    ap.add_argument("--runs", type=int, default=20)
    ap.add_argument("--ops", nargs="*", default=None,
                    help="subset of op names (default: all)")
    ap.add_argument("--output", default=None, help="write JSON here")
    ap.add_argument("--format", default="table", choices=["table", "json"])
    args = ap.parse_args(argv)

    results = run(args.size, args.warmup, args.runs, args.ops)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=2)
    if args.format == "json":
        print(json.dumps(results, indent=2))
    else:
        hdr = f"{'op':<20}{'mean(us)':>12}{'p50(us)':>12}{'p90(us)':>12}"
        print(hdr)
        print("-" * len(hdr))
        for name, r in results.items():
            if "error" in r:
                print(f"{name:<20}  ERROR: {r['error'][:50]}")
            else:
                print(f"{name:<20}{r['mean_us']:>12.1f}"
                      f"{r['p50_us']:>12.1f}{r['p90_us']:>12.1f}")


if __name__ == "__main__":
    main()
