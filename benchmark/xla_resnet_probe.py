"""Raw-jax ResNet-50 step-time probe: what can XLA itself do on this chip?

Measures fwd / fwd+bwd / fwd+bwd+sgd step time for a hand-rolled ResNet-50
in NCHW and NHWC layouts, bf16, outside the framework. This separates
"mxnet_tpu overhead" from "XLA conv behavior" when chasing BASELINE
config 2. Not a framework API — a diagnostic harness.

Usage: python benchmark/xla_resnet_probe.py [nchw|nhwc] [batch]
"""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax


def conv(x, w, stride, layout):
    if layout == "NCHW":
        dn = ("NCHW", "OIHW", "NCHW")
    else:
        dn = ("NHWC", "HWIO", "NHWC")
    kh = w.shape[2] if layout == "NCHW" else w.shape[0]
    pad = (kh - 1) // 2
    return lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)],
        dimension_numbers=dn)


def bn(x, scale, bias, layout):
    axes = (0, 2, 3) if layout == "NCHW" else (0, 1, 2)
    xf = x.astype(jnp.float32)
    m = xf.mean(axes, keepdims=True)
    v = xf.var(axes, keepdims=True)
    y = (xf - m) * lax.rsqrt(v + 1e-5)
    shape = [1, -1, 1, 1] if layout == "NCHW" else [1, 1, 1, -1]
    return (y * scale.reshape(shape) + bias.reshape(shape)).astype(x.dtype)


def make_params(rng, layout, dtype=jnp.bfloat16):
    """ResNet-50 v1: stem + [3,4,6,3] bottleneck stages + fc."""
    params = []
    keys = iter(jax.random.split(rng, 256))

    def w_conv(cin, cout, k):
        shape = ((cout, cin, k, k) if layout == "NCHW"
                 else (k, k, cin, cout))
        fan_in = cin * k * k
        return (jax.random.normal(next(keys), shape, jnp.float32)
                * (2.0 / fan_in) ** 0.5).astype(dtype)

    def w_bn(c):
        return (jnp.ones((c,), jnp.float32), jnp.zeros((c,), jnp.float32))

    stem = {"w": w_conv(3, 64, 7), "bn": w_bn(64)}
    stages = []
    cin = 64
    for stage_i, (blocks, cmid) in enumerate(
            zip([3, 4, 6, 3], [64, 128, 256, 512])):
        cout = cmid * 4
        stage = []
        for b in range(blocks):
            stride = 2 if (b == 0 and stage_i > 0) else 1
            blk = {
                "c1": w_conv(cin, cmid, 1), "bn1": w_bn(cmid),
                "c2": w_conv(cmid, cmid, 3), "bn2": w_bn(cmid),
                "c3": w_conv(cmid, cout, 1), "bn3": w_bn(cout),
            }
            if cin != cout or stride != 1:
                blk["proj"] = w_conv(cin, cout, 1)
                blk["bnp"] = w_bn(cout)
            stage.append(blk)
            cin = cout
        stages.append(stage)
    fc_w = (jax.random.normal(next(keys), (2048, 1000), jnp.float32)
            * 0.01).astype(dtype)
    fc_b = jnp.zeros((1000,), dtype)
    return {"stem": stem, "stages": stages, "fc": (fc_w, fc_b)}


def forward(params, x, layout):
    h = conv(x, params["stem"]["w"], 2, layout)
    h = jax.nn.relu(bn(h, *params["stem"]["bn"], layout))
    if layout == "NCHW":
        h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 1, 3, 3),
                              (1, 1, 2, 2), [(0, 0), (0, 0), (1, 1), (1, 1)])
    else:
        h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), [(0, 0), (1, 1), (1, 1), (0, 0)])
    for stage_i, stage in enumerate(params["stages"]):
        for b, blk in enumerate(stage):
            s = 2 if (b == 0 and stage_i > 0) else 1
            r = h
            h2 = jax.nn.relu(bn(conv(h, blk["c1"], 1, layout),
                                *blk["bn1"], layout))
            h2 = jax.nn.relu(bn(conv(h2, blk["c2"], s, layout),
                                *blk["bn2"], layout))
            h2 = bn(conv(h2, blk["c3"], 1, layout), *blk["bn3"], layout)
            if "proj" in blk:
                r = bn(conv(r, blk["proj"], s, layout),
                       *blk["bnp"], layout)
            h = jax.nn.relu(h2 + r)
    axes = (2, 3) if layout == "NCHW" else (1, 2)
    pooled = h.astype(jnp.float32).mean(axes)
    w, b = params["fc"]
    return pooled @ w.astype(jnp.float32) + b.astype(jnp.float32)


def loss_fn(params, x, y, layout):
    logits = forward(params, x, layout)
    lse = jax.nn.logsumexp(logits, axis=-1)
    true = jnp.take_along_axis(logits, y[:, None], 1)[:, 0]
    return (lse - true).mean()


def time_call(fn, *args, n=20):
    r = fn(*args)
    r = fn(*args)  # relayout recompile
    leaves = jax.tree_util.tree_leaves(r)
    onp.asarray(leaves[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn(*args)
    leaves = jax.tree_util.tree_leaves(r)
    onp.asarray(leaves[0]).ravel()[:1]
    return (time.perf_counter() - t0) / n


def main():
    layout = sys.argv[1].upper() if len(sys.argv) > 1 else "NCHW"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    rng = jax.random.PRNGKey(0)
    params = jax.device_put(make_params(rng, layout), jax.devices()[0])
    shape = ((batch, 3, 224, 224) if layout == "NCHW"
             else (batch, 224, 224, 3))
    x = jax.device_put(
        jnp.asarray(onp.random.RandomState(0).uniform(-1, 1, shape),
                    jnp.bfloat16), jax.devices()[0])
    y = jax.device_put(
        jnp.asarray(onp.random.RandomState(1).randint(0, 1000, (batch,)),
                    jnp.int32), jax.devices()[0])

    # IMPORTANT: all timed jits return SCALARS — the axon tunnel streams
    # large jit outputs back to the host (~370 MB/s measured), so returning
    # grads/params from a timed fn measures the network, not the chip.
    fwd = jax.jit(functools.partial(loss_fn, layout=layout))
    dt = time_call(fwd, params, x, y)
    print(f"[{layout} b{batch}] fwd+loss     {dt*1e3:7.2f} ms "
          f"({batch/dt:7.1f} img/s)")

    @jax.jit
    def grad_scalar(params, x, y):
        g = jax.grad(functools.partial(loss_fn, layout=layout))(params, x, y)
        return sum(l.astype(jnp.float32).sum()
                   for l in jax.tree_util.tree_leaves(g))

    dt = time_call(grad_scalar, params, x, y)
    print(f"[{layout} b{batch}] fwd+bwd      {dt*1e3:7.2f} ms "
          f"({batch/dt:7.1f} img/s)")


if __name__ == "__main__":
    main()
