"""A/B probe: Pallas prologue-fused 1x1 conv vs the unfused XLA chain.

Per-junction times at ResNet-50 b128 bottleneck shapes, measured as a
lax.scan of ITERS repetitions inside ONE jit — the axon tunnel's ~3ms
per-call dispatch floor otherwise swamps sub-ms kernels (the first
version of this probe measured pure dispatch).  Each scan iteration
depends on the previous through a scalar, so XLA cannot batch or DCE
the op; the reported time is (t_scan - t_null) / ITERS.

Junction 3 (affine+relu -> conv3) and junction 1 (relu -> next conv1)
shapes; fwd and fwd+bwd arms, fused vs unfused.

Usage: python benchmark/fused_conv_probe.py [batch]
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from mxnet_tpu.ops.pallas.conv_fused import fused_prologue_conv1x1

# (Ci, Co, HW) at b128 — junction 3 (affine+relu) then junction 1 (relu)
J3 = [(64, 256, 56), (128, 512, 28), (256, 1024, 14), (512, 2048, 7)]
J1 = [(256, 64, 56), (512, 128, 28), (1024, 256, 14), (2048, 512, 7)]
ITERS = 20


def timed(fn, *args, n=5, static=()):
    import numpy as onp
    f = jax.jit(fn, static_argnums=static)
    # device_get, not block_until_ready: axon results are lazy handles
    # that only execute remotely when a value is actually fetched
    onp.asarray(f(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        onp.asarray(f(*args))   # fetch forces execution; RTT cancels
    t1 = time.perf_counter()    # against the null-scan arm
    return (t1 - t0) / n


def unfused(x, w, scale, shift, affine):
    a = x.astype(jnp.float32)
    if affine:
        a = a * scale.reshape(1, -1, 1, 1) + shift.reshape(1, -1, 1, 1)
    h = jnp.maximum(a, 0.0).astype(x.dtype)
    return lax.conv_general_dilated(
        h, w[:, :, None, None], (1, 1), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def fused(x, w, scale, shift, affine):
    return fused_prologue_conv1x1(x, w, scale if affine else None,
                                  shift if affine else None, relu=True)


def scan_fwd(impl, x, w, scale, shift, affine):
    def body(c, _):
        y = impl(x + c.astype(x.dtype), w, scale, shift, affine)
        # full-tensor reduction: a single-element carry lets XLA slice
        # the whole conv away (the first version measured nothing)
        return jnp.max(y).astype(jnp.float32) * 1e-9, None
    c, _ = lax.scan(body, jnp.float32(0), None, length=ITERS)
    return c


def scan_bwd(impl, x, w, scale, shift, affine, dy):
    if affine:
        def f(x, w, scale, shift):
            y = impl(x, w, scale, shift, True)
            return jnp.sum(y.astype(jnp.float32) * dy)
        g = jax.grad(f, argnums=(0, 1, 2, 3))
        def body(c, _):
            gx, gw, gs, gt = g(x + c.astype(x.dtype), w, scale, shift)
            return (jnp.max(gx).astype(jnp.float32)
                    + jnp.max(gw).astype(jnp.float32)
                    + jnp.max(gs) + jnp.max(gt)) * 1e-9, None
    else:
        def f(x, w):
            y = impl(x, w, None, None, False)
            return jnp.sum(y.astype(jnp.float32) * dy)
        g = jax.grad(f, argnums=(0, 1))
        def body(c, _):
            gx, gw = g(x + c.astype(x.dtype), w)
            return (jnp.max(gx).astype(jnp.float32)
                    + jnp.max(gw).astype(jnp.float32)) * 1e-9, None
    c, _ = lax.scan(body, jnp.float32(0), None, length=ITERS)
    return c


def scan_null(x):
    def body(c, _):
        return c + x.astype(jnp.float32)[0, 0, 0, 0] * 1e-9, None
    c, _ = lax.scan(body, jnp.float32(0), None, length=ITERS)
    return c


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    key = jax.random.PRNGKey(0)
    for affine, shapes, tag in [(True, J3, "j3 bn+relu->1x1"),
                                (False, J1, "j1    relu->1x1")]:
        for Ci, Co, HW in shapes:
            ks = jax.random.split(key, 5)
            x = jax.random.normal(ks[0], (B, Ci, HW, HW)).astype(jnp.bfloat16)
            w = (jax.random.normal(ks[1], (Co, Ci)) * 0.05).astype(jnp.bfloat16)
            scale = jax.random.uniform(ks[2], (Ci,)) + 0.5
            shift = jax.random.normal(ks[3], (Ci,)) * 0.1
            dy = jax.random.normal(ks[4], (B, Co, HW, HW)).astype(jnp.float32)
            x, w, scale, shift, dy = jax.device_put((x, w, scale, shift, dy))

            import functools
            t0 = timed(scan_null, x)
            per = {}
            for name, impl in (("ref", unfused), ("fus", fused)):
                # arrays ride as jit ARGUMENTS — a closure capture would
                # embed them as HLO constants and blow the remote-compile
                # tunnel's request size limit
                tf = (timed(functools.partial(scan_fwd, impl),
                            x, w, scale, shift, affine,
                            static=(4,)) - t0) / ITERS
                tb = (timed(functools.partial(scan_bwd, impl),
                            x, w, scale, shift, affine, dy,
                            static=(4,)) - t0) / ITERS
                per[name] = (tf, tb)
            rf, rb = per["ref"]
            ff, fb = per["fus"]
            print(f"{tag} Ci={Ci:4d} Co={Co:4d} {HW}x{HW}: "
                  f"fwd {rf*1e3:6.2f} -> {ff*1e3:6.2f} ms ({rf/ff:4.2f}x) | "
                  f"fwd+bwd {rb*1e3:6.2f} -> {fb*1e3:6.2f} ms "
                  f"({rb/fb:4.2f}x)", flush=True)


if __name__ == "__main__":
    main()
