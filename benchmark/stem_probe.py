"""Is the ResNet stem (7x7 s2 conv on C=3) worth a space-to-depth rewrite?

Times fwd+bwd of: (a) the standard stem conv, (b) the mathematically
equivalent space-to-depth form (2x2 patches -> C=12, 4x4 s1 kernel),
(c) the rest-of-network first bottleneck conv for scale. Diagnostic only.
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax


def timeit(fn, *args, n=30):
    fn(*args)
    fn(*args)
    r = fn(*args)
    onp.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[:1]
    t0 = time.perf_counter()
    for _ in range(n):
        r = fn(*args)
    onp.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[:1]
    return (time.perf_counter() - t0) / n


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    dev = jax.devices()[0]
    rng = onp.random.RandomState(0)
    x = jax.device_put(jnp.asarray(
        rng.uniform(-1, 1, (batch, 3, 224, 224)), jnp.bfloat16), dev)
    w = jax.device_put(jnp.asarray(
        rng.uniform(-0.1, 0.1, (64, 3, 7, 7)), jnp.bfloat16), dev)

    def stem(x, w):
        return lax.conv_general_dilated(
            x, w, (2, 2), [(3, 3), (3, 3)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))

    def loss_std(x, w):
        return stem(x, w).astype(jnp.float32).sum()

    g_std = jax.jit(jax.grad(loss_std, argnums=(0, 1)))
    dt = timeit(g_std, x, w)
    print(f"stem 7x7s2 C3 fwd+bwd: {dt*1e3:.2f} ms")

    # space-to-depth: pad W to kernel 8, pack 2x2 spatial into channels.
    # y[n,o,i,j] = sum_{c,p,q} x[n,c,2i+p-3,2j+q-3] w[o,c,p,q]  (7x7, pad 3)
    # With x2[n, c*4 + (di*2+dj), I, J] = x[n, c, 2I+di, 2J+dj] the same sum
    # is a 4x4 s1 conv over 12 channels (kernel w2 scattered from w).
    def pack_x(x):
        B, C, H, W = x.shape
        xp = jnp.pad(x, ((0, 0), (0, 0), (3, 5), (3, 5)))  # 224 -> 232 even
        Hp = (H + 8) // 2
        xr = xp.reshape(B, C, Hp, 2, Hp, 2)
        return xr.transpose(0, 1, 3, 5, 2, 4).reshape(B, C * 4, Hp, Hp)

    def pack_w(w):
        O, C, KH, KW = w.shape
        wp = jnp.pad(w, ((0, 0), (0, 0), (0, 1), (0, 1)))  # 7->8
        wr = wp.reshape(O, C, 4, 2, 4, 2)
        return wr.transpose(0, 1, 3, 5, 2, 4).reshape(O, C * 4, 4, 4)

    def loss_s2d(x, w):
        x2 = pack_x(x)
        w2 = pack_w(w)
        y = lax.conv_general_dilated(
            x2, w2, (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))[:, :, :112, :112]
        return y.astype(jnp.float32).sum()

    # correctness first
    y1 = stem(x[:2].astype(jnp.float32), w.astype(jnp.float32))
    x2 = pack_x(x[:2].astype(jnp.float32))
    w2 = pack_w(w.astype(jnp.float32))
    y2 = lax.conv_general_dilated(
        x2, w2, (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))[:, :, :112, :112]
    err = float(jnp.abs(y1 - y2).max())
    print(f"s2d equivalence max err: {err:.2e} (shapes {y1.shape} {y2.shape})")

    g_s2d = jax.jit(jax.grad(loss_s2d, argnums=(0, 1)))
    dt = timeit(g_s2d, x, w)
    print(f"stem s2d 4x4 C12 fwd+bwd: {dt*1e3:.2f} ms")

    # scale reference: one mid-network conv
    h = jax.device_put(jnp.asarray(
        rng.uniform(-1, 1, (batch, 256, 56, 56)), jnp.bfloat16), dev)
    wk = jax.device_put(jnp.asarray(
        rng.uniform(-0.1, 0.1, (64, 256, 1, 1)), jnp.bfloat16), dev)

    def loss_mid(h, wk):
        y = lax.conv_general_dilated(
            h, wk, (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        return y.astype(jnp.float32).sum()

    g_mid = jax.jit(jax.grad(loss_mid, argnums=(0, 1)))
    dt = timeit(g_mid, h, wk)
    print(f"mid 1x1 C256->64 fwd+bwd: {dt*1e3:.2f} ms")


if __name__ == "__main__":
    main()
