"""Isolated attention A/B probe on the real chip.

Times fwd(+bwd) of dot-product attention variants at a given shape, with
the tunnel measurement rules applied (memory: axon-tunnel-perf-traps):
ITERS steps run inside ONE jit via lax.scan and only a scalar returns, so
neither per-call dispatch (~120 ms) nor output streaming pollutes the
numbers. Two warmup calls absorb compile + first-execution relayout.

Usage:
  python benchmark/attn_probe.py --T 1024 2048 4096 --phase fwdbwd
Variants: xla (jax.nn.dot_product_attention), flash:BQxBK (our Pallas
kernel), jaxref (jax's bundled pallas flash kernel, probe-only target).
"""
import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax


def make_inputs(B, H, T, D, dtype):
    rng = onp.random.RandomState(0)
    dev = jax.devices()[0]
    mk = lambda: jax.device_put(
        jnp.asarray(rng.uniform(-1, 1, (B, H, T, D)).astype(onp.float32),
                    dtype=dtype), dev)
    return mk(), mk(), mk()


def xla_attn(q, k, v):
    # operates in (B, T, H, D); our probe arrays are (B, H, T, D)
    qt, kt, vt = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
    o = jax.nn.dot_product_attention(qt, kt, vt, is_causal=True)
    return jnp.swapaxes(o, 1, 2)


def ours(q, k, v, bq, bk):
    from mxnet_tpu.ops.pallas.attention import _flash2
    return _flash2(q, k, v, None, None, 0.0, 1.0 / (q.shape[-1] ** 0.5),
                   True, bq, bk, False)


def jaxref(q, k, v):
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as ja)
    return ja(q, k, v, causal=True,
              sm_scale=float(1.0 / (q.shape[-1] ** 0.5)))


def timed(fn, q, k, v, iters, phase):
    if phase == "fwd":
        def one(c, _):
            qq, kk, vv = c
            o = fn(qq, kk, vv)
            return (qq + 1e-6 * o, kk, vv), jnp.float32(0)
    else:
        def loss(qq, kk, vv):
            return jnp.sum(fn(qq, kk, vv).astype(jnp.float32))
        g = jax.grad(loss, argnums=(0, 1, 2))

        def one(c, _):
            qq, kk, vv = c
            dq, dk, dv = g(qq, kk, vv)
            return (qq + 1e-6 * dq, kk + 1e-6 * dk, vv + 1e-6 * dv), \
                jnp.float32(0)

    def run(qq, kk, vv):
        (qq, kk, vv), _ = lax.scan(one, (qq, kk, vv), None, length=iters)
        return jnp.sum(qq[0, 0, 0]).astype(jnp.float32)

    jr = jax.jit(run)
    for _ in range(2):
        float(jr(q, k, v))          # compile + relayout warmup
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(jr(q, k, v))
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--B", type=int, default=8)
    ap.add_argument("--H", type=int, default=12)
    ap.add_argument("--D", type=int, default=64)
    ap.add_argument("--T", type=int, nargs="+", default=[1024])
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument("--phase", default="fwdbwd", choices=["fwd", "fwdbwd"])
    ap.add_argument("--variants", nargs="+",
                    default=["xla", "flash:128x128", "flash:256x256",
                             "flash:512x512", "flash:256x512", "jaxref"])
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args()

    dtype = jnp.dtype(args.dtype)
    for T in args.T:
        q, k, v = make_inputs(args.B, args.H, T, args.D, dtype)
        # causal fwd flops: 2 matmuls * B*H*T^2*D*2 / 2 (causal half)
        flops = args.B * args.H * T * T * args.D * 4 / 2
        if args.phase == "fwdbwd":
            flops *= 3.5            # dq + dkv recompute + 5 matmuls bwd
        for name in args.variants:
            if name == "xla":
                fn = xla_attn
            elif name == "jaxref":
                fn = jaxref
            elif name.startswith("flash:"):
                bq, bk = map(int, name.split(":")[1].split("x"))
                # clamp to T like flash_attention does (a whole-T k
                # block engages the fused single-pass backward)
                bq, bk = min(bq, T), min(bk, T)
                fn = functools.partial(ours, bq=bq, bk=bk)
            else:
                raise SystemExit(f"unknown variant {name}")
            try:
                dt = timed(fn, q, k, v, args.iters, args.phase)
                print(f"T={T:5d} {name:14s} {dt * 1e3:8.3f} ms/step "
                      f"{flops / dt / 1e12:6.1f} TFLOP/s", flush=True)
            except Exception as e:
                print(f"T={T:5d} {name:14s} FAILED: {type(e).__name__}: "
                      f"{str(e)[:200]}", flush=True)


if __name__ == "__main__":
    main()
