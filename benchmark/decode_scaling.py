"""JPEG-decode worker-scaling curve (VERDICT r4 weak 5 / directive 6).

Measures gluon DataLoader throughput over an im2rec-style JPEG pack at
num_workers = 0, 1, 2, 4: decode+augment per image in worker processes,
batchified to uint8 NHWC — the multi-worker half of the real-data path
(`src/io/iter_image_recordio_2.cc` decode-thread analog).  Workers run
under the loader's spawn start method (r6: fork-after-jax deadlocked
this probe the moment `ImageRecordDataset.__getitem__` returned a
jax-backed NDArray — VERDICT r5 weak 1), so the transform below must be
module-level (it ships to workers by pickle).  On a 1-core rig the
curve documents the SHARING penalty (workers multiplex one core); on a
real multi-core TPU-VM host the same code scales.

    python benchmark/decode_scaling.py [n_images]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as onp


def center_crop_224(img, label):
    """Module-level so it pickles into spawned workers."""
    a = img.asnumpy() if hasattr(img, "asnumpy") else onp.asarray(img)
    y0 = (a.shape[0] - 224) // 2
    x0 = (a.shape[1] - 224) // 2
    return onp.ascontiguousarray(a[y0:y0 + 224, x0:x0 + 224]), label


def main():
    n_rec = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    from bench import _build_bench_pack
    import mxnet_tpu as mx  # noqa: F401 - jax config + package init
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.vision.datasets import ImageRecordDataset

    pack = _build_bench_pack(f"/tmp/mxtpu_decode_jpg_{n_rec}_256",
                             n_rec, 256, "jpg")
    ds = ImageRecordDataset(pack)

    batch = 32
    for workers in (0, 1, 2, 4):
        dl = DataLoader(ds.transform(center_crop_224), batch_size=batch,
                        num_workers=workers, shuffle=False)
        # one warm epoch (worker spawn, page cache)
        for _ in dl:
            pass
        t0 = time.perf_counter()
        n = 0
        for xb, yb in dl:
            n += xb.shape[0]
        dt = time.perf_counter() - t0
        print(f"workers={workers}: {n / dt:8.1f} img/s "
              f"({n} imgs, {dt * 1e3:.0f} ms)")


if __name__ == "__main__":
    main()
