"""Per-stage/per-component attribution of the ResNet-50 b128 train step.

VERDICT r4 weak 1: the headline has been flat at ~2,470 img/s while the
roofline proves the conv shapes run at 151-190 TFLOP/s in isolation —
so where do the milliseconds actually go?  This probe answers by
DIFFERENCE (the roofline's method, robust to the tunnel's fixed costs):

* truncated networks (stem, +stage1, ..., +stage4, +head) — successive
  differences attribute fwd+bwd time per stage;
* component ablations at the full depth — batch-stat BN swapped for a
  frozen scale/bias (quantifies the stats round-trips), ReLU removed
  (quantifies activation fusion), convs-only;
* a per-shape conv roofline check inside the real context.

All variants time fwd+bwd+(sgd update) of the SAME hand-rolled bf16
NCHW ResNet-50 as xla_resnet_probe (raw jax — framework overhead is
already known to be ~nil: raw 2,276 img/s vs framework 2,469).

Usage: python benchmark/resnet_layer_probe.py [batch]
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as onp
from jax import lax

sys.path.insert(0, "/root/repo")
from benchmark.xla_resnet_probe import (bn, conv, forward, loss_fn,
                                        make_params)


def bn_frozen(x, scale, bias, layout):
    """Scale/bias only — no batch statistics (the ablation arm)."""
    shape = [1, -1, 1, 1] if layout == "NCHW" else [1, 1, 1, -1]
    xf = x.astype(jnp.float32)
    return (xf * scale.reshape(shape)
            + bias.reshape(shape)).astype(x.dtype)


def forward_ablate(params, x, layout, bn_fn, use_relu=True, depth=99):
    """forward() with swappable BN/ReLU and a stage-truncation depth:
    depth 0 = stem only, 1..4 = through stage N, 99 = full net."""
    act = jax.nn.relu if use_relu else (lambda a: a)
    h = conv(x, params["stem"]["w"], 2, layout)
    h = act(bn_fn(h, *params["stem"]["bn"], layout))
    h = lax.reduce_window(h, -jnp.inf, lax.max, (1, 1, 3, 3),
                          (1, 1, 2, 2), [(0, 0), (0, 0), (1, 1), (1, 1)])
    if depth == 0:
        return h
    for stage_i, stage in enumerate(params["stages"]):
        if stage_i >= depth:
            return h
        for b, blk in enumerate(stage):
            s = 2 if (b == 0 and stage_i > 0) else 1
            r = h
            h2 = act(bn_fn(conv(h, blk["c1"], 1, layout),
                           *blk["bn1"], layout))
            h2 = act(bn_fn(conv(h2, blk["c2"], s, layout),
                           *blk["bn2"], layout))
            h2 = bn_fn(conv(h2, blk["c3"], 1, layout), *blk["bn3"], layout)
            if "proj" in blk:
                r = bn_fn(conv(r, blk["proj"], s, layout),
                          *blk["bnp"], layout)
            h = act(h2 + r)
    pooled = h.astype(jnp.float32).mean((2, 3))
    w, b = params["fc"]
    return pooled @ w.astype(jnp.float32) + b.astype(jnp.float32)


def timed_grad(fn, params, x, y, n=20):
    g = jax.jit(jax.grad(fn))
    r = g(params, x, y)
    r = g(params, x, y)
    jax.tree_util.tree_leaves(r)[0].block_until_ready()
    t0 = time.perf_counter()
    for _ in range(n):
        r = g(params, x, y)
    jax.tree_util.tree_leaves(r)[0].block_until_ready()
    return (time.perf_counter() - t0) / n


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    layout = "NCHW"
    rng = jax.random.PRNGKey(0)
    params = make_params(rng, layout)
    params = jax.device_put(params)
    x = jax.device_put(
        jax.random.normal(rng, (B, 3, 224, 224), jnp.float32)
        .astype(jnp.bfloat16))
    y = jax.device_put(
        jax.random.randint(rng, (B,), 0, 1000, jnp.int32))

    def loss_of(bn_fn, use_relu=True, depth=99):
        def f(p, x, y):
            h = forward_ablate(p, x, layout, bn_fn, use_relu, depth)
            if depth != 99:
                return (h.astype(jnp.float32) ** 2).mean()
            lse = jax.nn.logsumexp(h, axis=-1)
            true = jnp.take_along_axis(h, y[:, None], 1)[:, 0]
            return (lse - true).mean()
        return f

    full = timed_grad(loss_of(bn), params, x, y)
    print(f"full fwd+bwd           {full * 1e3:8.2f} ms "
          f"({B / full:7.1f} img/s)")

    nobn = timed_grad(loss_of(bn_frozen), params, x, y)
    print(f"frozen-BN (no stats)   {nobn * 1e3:8.2f} ms "
          f"(stats cost {1e3 * (full - nobn):6.2f} ms)")

    norelu = timed_grad(loss_of(bn, use_relu=False), params, x, y)
    print(f"no-ReLU                {norelu * 1e3:8.2f} ms "
          f"(relu cost {1e3 * (full - norelu):6.2f} ms)")

    both = timed_grad(loss_of(bn_frozen, use_relu=False), params, x, y)
    print(f"convs+residual only    {both * 1e3:8.2f} ms")

    prev = 0.0
    for depth, name in [(0, "stem+pool"), (1, "stage1"), (2, "stage2"),
                        (3, "stage3"), (4, "stage4")]:
        t = timed_grad(loss_of(bn, depth=depth), params, x, y)
        print(f"through {name:<10}     {t * 1e3:8.2f} ms "
              f"(+{1e3 * (t - prev):6.2f} ms)")
        prev = t


if __name__ == "__main__":
    main()
