"""Imperative-mode dispatch benchmark (TPU-resident eager execution).

Measures per-op dispatch cost of the executable cache on the accelerator
(BASELINE: the reference's ~10-30us python->PushAsync path; through the
axon tunnel the floor is network RTT, so the interesting number is
amortized async dispatch, not sync round-trip). Also verifies the VERDICT
done-criteria: imperative MLP + ResNet-block steps execute on the TPU
backend with eager output buffers on-device.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.ndarray import register as reg

    ctx = mx.tpu()
    dev = ctx.jax_device
    print(f"accelerator: {dev} (platform {dev.platform})")

    with ctx:
        a = mx.np.array(onp.random.RandomState(0)
                        .uniform(-1, 1, (256, 256)).astype("float32"))
        b = mx.np.array(onp.random.RandomState(1)
                        .uniform(-1, 1, (256, 256)).astype("float32"))
        # warm the executable
        c = mx.np.dot(a, b)
        print("eager output devices:", {d.platform for d in c._data.devices()},
              "| cache entries:", len(reg._EXEC_CACHE))
        c.asnumpy()

        n = 50
        t0 = time.perf_counter()
        x = a
        for _ in range(n):
            x = mx.np.dot(x, b)
        x.asnumpy()
        dt = (time.perf_counter() - t0) / n
        print(f"chained dot dispatch (cached): {dt*1e3:.2f} ms/op")

        # imperative MLP fwd+bwd+sgd on-device
        mx.random.seed(0)
        net = mx.gluon.nn.HybridSequential()
        net.add(mx.gluon.nn.Dense(256, activation="relu"),
                mx.gluon.nn.Dense(64, activation="relu"),
                mx.gluon.nn.Dense(10))
        net.initialize(ctx=ctx)
        tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.05})
        X = mx.np.array(onp.random.RandomState(2)
                        .uniform(-1, 1, (64, 128)).astype("float32"))
        Y = mx.np.array(onp.random.RandomState(3)
                        .randint(0, 10, (64,)).astype("int32"))
        lf = mx.gluon.loss.SoftmaxCrossEntropyLoss()
        with autograd.record():
            loss = lf(net(X), Y).mean()
        loss.backward()
        tr.step(1)
        w = net[0].weight.data()
        print("MLP imperative step OK; param devices:",
              {d.platform for d in w._data.devices()},
              "loss", float(loss.asnumpy()))

        t0 = time.perf_counter()
        for _ in range(10):
            with autograd.record():
                loss = lf(net(X), Y).mean()
            loss.backward()
            tr.step(1)
        loss.asnumpy()
        dt = (time.perf_counter() - t0) / 10
        print(f"MLP imperative fwd+bwd+sgd: {dt*1e3:.1f} ms/step")

        # ResNet basic block, imperative
        class Block(mx.gluon.HybridBlock):
            def __init__(self):
                super().__init__()
                self.c1 = mx.gluon.nn.Conv2D(64, 3, padding=1)
                self.b1 = mx.gluon.nn.BatchNorm()
                self.c2 = mx.gluon.nn.Conv2D(64, 3, padding=1)
                self.b2 = mx.gluon.nn.BatchNorm()

            def forward(self, x):
                h = mx.npx.relu(self.b1(self.c1(x)))
                return mx.npx.relu(self.b2(self.c2(h)) + x)

        blk = Block()
        blk.initialize(ctx=ctx)
        xb = mx.np.array(onp.random.RandomState(4)
                         .uniform(-1, 1, (16, 64, 32, 32)).astype("float32"))
        trb = mx.gluon.Trainer(blk.collect_params(), "sgd",
                               {"learning_rate": 0.05})
        with autograd.record():
            out = blk(xb)
            l2 = (out * out).mean()
        l2.backward()
        trb.step(1)
        print("ResNet-block imperative step OK; out devices:",
              {d.platform for d in out._data.devices()},
              "loss", float(l2.asnumpy()))
        t0 = time.perf_counter()
        for _ in range(10):
            with autograd.record():
                out = blk(xb)
                l2 = (out * out).mean()
            l2.backward()
            trb.step(1)
        l2.asnumpy()
        dt = (time.perf_counter() - t0) / 10
        print(f"ResNet-block imperative fwd+bwd+sgd: {dt*1e3:.1f} ms/step")


if __name__ == "__main__":
    main()
