#!/usr/bin/env bash
# CI driver — the reference's ci/build.py + runtime_functions.sh analog
# (SURVEY.md §2.7): every supported build/test variant behind one entry
# point. Usage:
#
#   ci/run.sh native        # build libmxtpu.so + run the C++ test binary
#   ci/run.sh tier1         # docs-freshness gates + serving smoke +
#                           #   chaos smoke + the tier-1 pytest
#                           #   selection (the driver's acceptance run)
#   ci/run.sh mxlint        # the AST concurrency/invariant analyzer
#                           #   (lock discipline, determinism hygiene,
#                           #   donation safety, registration
#                           #   completeness + doc freshness) — fails
#                           #   on any unwaived finding or stale
#                           #   waiver; ci/mxlint_waivers.toml
#   ci/run.sh envdoc        # thin alias: the analyzer's env-surface
#                           #   rules alone (MX-R001 + MX-R004)
#   ci/run.sh faultdoc      # thin alias: the analyzer's fault-site
#                           #   doc rule alone (MX-R003)
#   ci/run.sh serving-smoke # tools/serve_bench.py --smoke alone
#                           #   (batching wins / bounded compiles /
#                           #   shed-not-crash)
#   ci/run.sh generation-smoke # continuous-batching generation gate:
#                           #   mixed prompt-length traffic at 8
#                           #   clients, >=2x tokens/sec vs sequential
#                           #   one-shot-per-token, 0 decode compiles
#                           #   after warmup, clean shed under a
#                           #   2x-slot flood; PLUS the speculative
#                           #   leg: draft/verify >=1.3x tokens/sec,
#                           #   accepted/step >1.0, byte-identical
#                           #   streams, rollback + worker-kill legs
#   ci/run.sh resilience-smoke # serving resilience gate: seeded
#                           #   worker-kill mid-stream -> every stream
#                           #   completes token-identical to the
#                           #   fault-free run on the raw wire;
#                           #   SIGTERM under 8-client load -> clean
#                           #   drain (429 sheds, readiness 503 /
#                           #   liveness 200, exit 0)
#   ci/run.sh dist-resilience-smoke # elastic distributed training
#                           #   gate: seeded ps.server crash mid-
#                           #   training at 2 workers -> supervised
#                           #   restart + snapshot restore + exactly-
#                           #   once parity; worker kill -> auto-
#                           #   resume completes exactly; restart-
#                           #   budget exhaustion degrades (exit 70)
#   ci/run.sh chaos-smoke   # bounded fault-injection/preemption proof
#                           #   (tests/test_faults.py -k smoke)
#   ci/run.sh cache-smoke   # persistent compile cache warm-restart
#                           #   gate: cold run compiles N + persists,
#                           #   restarted training job and serving
#                           #   replica compile 0 with bit-identical
#                           #   losses/tokens, a fully poisoned cache
#                           #   + seeded read/write fault plan
#                           #   degrades to quarantine+recompile with
#                           #   0 caller-visible errors
#   ci/run.sh health-smoke  # training health guard acceptance: seeded
#                           #   NaN plan -> exactly one skip + loss
#                           #   recovery + budget; watchdog stack dump
#                           #   on an injected stall; replay identical
#   ci/run.sh dist-comm-smoke # overlapped-collectives gate: bucketed
#                           #   priority-scheduled gradient reduction
#                           #   >=1.3x steps/sec vs serialized on a
#                           #   calibrated synthetic-slow wire, loss
#                           #   bit-parity / 2bit replay determinism,
#                           #   0 compiles after warmup
#   ci/run.sh input-pipeline-smoke # async device-prefetch gate:
#                           #   synthetic slow loader + real step ->
#                           #   steps/sec ~ max(loader, step) not the
#                           #   sum, <10% stall with a hidden loader,
#                           #   majority-stall demonstrated unpiped,
#                           #   0 compiles after warmup, loss parity
#   ci/run.sh trace-smoke   # distributed-tracing gate: a traced
#                           #   generation request shows HTTP -> queue
#                           #   -> prefill -> >=1 linked iteration ->
#                           #   first-token spans under ONE trace id on
#                           #   the raw /v1/traces wire; traced train
#                           #   steps show prefetch / backward-segment
#                           #   / bucket / optimizer children and a
#                           #   ps.handle remote child across the PS
#                           #   frame; 1%-sampling steps/sec >=0.97x
#                           #   tracing-off, 0 compiles after warmup
#   ci/run.sh bench-check   # bench regression gate (bench.py --check):
#                           #   deterministic metrics (compiles after
#                           #   warmup, flush growth, stall fraction)
#                           #   FAIL; wall-clock vs ROUND_BASELINES
#                           #   only WARNS (rig noise is +/-25-40%)
#   ci/run.sh chaos         # full chaos suite incl. SIGKILL/SIGTERM
#                           #   subprocess resume proofs
#   ci/run.sh bulk-smoke    # lazy-bulking acceptance: lstm micro-run
#                           #   (dispatch reduction / steady cache /
#                           #   loss parity)
#   ci/run.sh bulk-off      # core suite with MXNET_BULK_MAX_OPS=1
#                           #   (per-op dispatch sanitizer)
#   ci/run.sh unit          # full Python suite on the 8-dev virtual mesh
#   ci/run.sh dist          # real multi-process launcher tests
#   ci/run.sh exec-cache    # suite subset with the per-op executable
#                           #   cache FORCED on (our sanitizer analog:
#                           #   flushes out cache-vs-eager divergence)
#   ci/run.sh naive-engine  # subset under MXNET_ENGINE_TYPE=NaiveEngine
#                           #   (fully synchronous — the race-debug mode)
#   ci/run.sh dryrun        # multichip sharding dry run + entry compile
#   ci/run.sh tpu-sweep     # op sweep against the real chip
#                           #   (MXNET_TEST_CTX=tpu ctx-flip)
#   ci/run.sh tpu-core      # sweep + core-file sample on the chip
#                           #   (~510 tests, the tractable chip gate)
#   ci/run.sh tpu-unit      # the WHOLE suite with default ctx = tpu
#                           #   (test_operator_gpu.py "rerun everything
#                           #   on the accelerator" analog)
#   ci/run.sh tpu-unit-batched  # same gate file-by-file with an
#                           #   incremental log (partial evidence
#                           #   survives tunnel hiccups)
#   ci/run.sh all           # native + unit + dist + exec-cache +
#                           #   naive-engine + dryrun
set -euo pipefail
cd "$(dirname "$0")/.."

variant="${1:-all}"

run_native() {
  echo "== native: build libmxtpu.so + C++ tests"
  make -C src
  make -C src test
}

run_mxlint() {
  echo "== mxlint: AST concurrency & invariant analyzer — lock"
  echo "   discipline (blocking-under-lock, lock-order cycles),"
  echo "   determinism hygiene on seeded fault paths, donation safety,"
  echo "   registration completeness (env vars, metric families, fault"
  echo "   sites) + docs/env_vars.md freshness.  Waivers:"
  echo "   ci/mxlint_waivers.toml (unused waivers are errors)"
  # MXNET_NO_AUTO_DISTRIBUTED: the lint must never join a training
  # job's coordinator just because the env leaked into this shell
  JAX_PLATFORMS=cpu MXNET_NO_AUTO_DISTRIBUTED=1 timeout 120 \
    python -m mxnet_tpu.analysis
}

run_envdoc() {
  # thin alias kept for existing invocations — the analyzer subsumed
  # the old regen+git-diff check (MX-R004 render-compares, so a dirty
  # tree lints the same as a clean one)
  echo "== envdoc: env-var surface rules (mxlint MX-R001 + MX-R004)"
  JAX_PLATFORMS=cpu MXNET_NO_AUTO_DISTRIBUTED=1 \
    python -m mxnet_tpu.analysis --rules MX-R001,MX-R004
}

run_serving_smoke() {
  echo "== serving-smoke: dynamic batching beats batch-1, bucketed"
  echo "   compiles stay bounded, overload sheds without crashing"
  JAX_PLATFORMS=cpu python tools/serve_bench.py --smoke
}

run_generation_smoke() {
  echo "== generation-smoke: continuous batching >=2x sequential"
  echo "   one-shot-per-token, 0 decode recompiles after warmup"
  echo "   (incl. across sampled method/param changes — traced"
  echo "   operands), same-seed sampled streams identical, hot-prefix"
  echo "   TTFT p50 <=0.5x cold prefill with byte-identical streams,"
  echo "   2x-slot flood sheds cleanly (tokens/sec + TTFT reported;"
  echo "   the noisy throughput gate gets one re-measure on a miss)"
  JAX_PLATFORMS=cpu timeout 900 python tools/serve_bench.py \
    --generate --smoke
  echo "== generation-smoke (speculative): draft/verify decoding"
  echo "   >=1.3x tokens/sec over the non-speculative engine,"
  echo "   accepted-tokens/step >1.0, greedy AND sampled streams"
  echo "   byte-identical at the same seeds, truncated-draft leg"
  echo "   rejects+rolls back KV rows without changing a byte, seeded"
  echo "   worker-kill replays speculative streams token-identically,"
  echo "   0 XLA compiles after warmup"
  JAX_PLATFORMS=cpu timeout 900 python tools/serve_bench.py \
    --generate --speculative --smoke
}

run_faultdoc() {
  # thin alias kept for existing invocations — the analyzer's static
  # MX-R003 rule subsumed the old runtime known_sites() grep
  echo "== faultdoc: fault-site doc rule (mxlint MX-R003)"
  JAX_PLATFORMS=cpu MXNET_NO_AUTO_DISTRIBUTED=1 \
    python -m mxnet_tpu.analysis --rules MX-R003
}

run_resilience_smoke() {
  echo "== resilience-smoke: worker-kill mid-stream recovers token-"
  echo "   identical (exactly-once on the chunked wire); SIGTERM under"
  echo "   8-client load drains clean (429 sheds, ready 503/live 200,"
  echo "   exit 0) — lock-order sanitizer armed (MXNET_SANITIZE=locks)"
  JAX_PLATFORMS=cpu MXNET_SANITIZE=locks timeout 600 \
    python tools/resilience_smoke.py
}

run_dist_resilience_smoke() {
  echo "== dist-resilience-smoke: seeded PS crash -> supervised restart"
  echo "   + snapshot restore + exactly-once parity; worker kill ->"
  echo "   auto-resume exact; budget exhaustion degrades explicitly"
  JAX_PLATFORMS=cpu timeout 600 python tools/dist_resilience_smoke.py
}

run_chaos_smoke() {
  echo "== chaos-smoke: bounded (~60s) fault-injection / preemption /"
  echo "   checkpoint-fallback / kvstore-timeout proof — lock-order"
  echo "   sanitizer armed (MXNET_SANITIZE=locks)"
  JAX_PLATFORMS=cpu MXNET_SANITIZE=locks timeout 300 \
    python -m pytest tests/test_faults.py \
    -k smoke -q -p no:cacheprovider
}

run_cache_smoke() {
  echo "== cache-smoke: persistent compile cache — cold compiles N +"
  echo "   durable writes, restarted training job and serving replica"
  echo "   compile 0 with bit-identical losses/tokens, poisoned cache"
  echo "   + seeded fault plan degrades to recompile with 0 errors"
  JAX_PLATFORMS=cpu timeout 600 python tools/cache_smoke.py
}

run_bulk_smoke() {
  echo "== bulk-smoke: lazy eager-op bulking acceptance — lstm micro-run"
  echo "   asserting >=1.3x eager->bulked dispatch reduction, 0 segment"
  echo "   compiles after warmup, and loss parity"
  JAX_PLATFORMS=cpu MXNET_BENCH_MODEL=bulk_smoke timeout 600 \
    python bench.py
}

run_bulk_off() {
  echo "== bulk-off: core suite with bulking DISABLED (per-op dispatch)"
  echo "   — flushes out bulked-vs-eager divergence, the bulking analog"
  echo "   of the exec-cache sanitizer"
  MXNET_BULK_MAX_OPS=1 python -m pytest -q \
    tests/test_bulk.py tests/test_autograd.py tests/test_ndarray.py \
    tests/test_gluon.py tests/test_numpy.py tests/test_rnn.py
}

run_health_smoke() {
  echo "== health-smoke: NaN sentry skip + loss recovery + budget,"
  echo "   hang-watchdog stack dump, deterministic replay"
  JAX_PLATFORMS=cpu timeout 300 python tools/health_smoke.py
}

run_input_pipeline_smoke() {
  echo "== input-pipeline-smoke: prefetched steps/sec ~ max(loader,"
  echo "   step) not their sum, stall <10% with a hidden loader vs"
  echo "   majority-stall unpiped, 0 compiles after warmup, loss parity"
  JAX_PLATFORMS=cpu timeout 300 python tools/input_smoke.py
}

run_dist_comm_smoke() {
  echo "== dist-comm-smoke: bucketed+overlapped gradient reduction"
  echo "   >=1.3x steps/sec vs the serialized push-all/pull-all path"
  echo "   on a calibrated synthetic-slow wire, losses bit-identical"
  echo "   (lossless ctypes) / replay-identical (2bit), 0 compiles"
  echo "   after warmup; PLUS the backward-overlap leg: per-layer"
  echo "   segmentation + grad-ready streaming >=1.5x serialized AND"
  echo "   strictly faster than optimizer-only overlap, bit-identical"
  echo "   losses, 0 steady-state compiles incl. a warm restart via"
  echo "   the persistent compile cache"
  # 900s: the backward-overlap + warm-restart legs roughly tripled
  # the smoke's work (~4min on the reference rig; 2x slow-host margin)
  JAX_PLATFORMS=cpu timeout 900 python tools/dist_comm_smoke.py
}

run_trace_smoke() {
  echo "== trace-smoke: end-to-end distributed tracing — one trace id"
  echo "   spans HTTP front end -> batcher queue -> engine prefill ->"
  echo "   linked iterations -> token stream on the raw /v1/traces"
  echo "   wire; train steps carry prefetch/backward-segment/bucket/"
  echo "   optimizer children + a ps.handle remote child via the PS"
  echo "   frame traceparent; 1%-sampled steps/sec >=0.97x tracing-off"
  echo "   with 0 compiles after warmup"
  JAX_PLATFORMS=cpu timeout 600 python tools/trace_smoke.py
}

run_bench_check() {
  echo "== bench-check: deterministic bench regressions fail (compiles"
  echo "   after warmup / flush growth / stall fraction); wall-clock"
  echo "   deltas vs ROUND_BASELINES only warn (rig noise +/-25-40%)"
  JAX_PLATFORMS=cpu timeout 600 python bench.py --check BENCH_r0*.json
}

run_chaos() {
  echo "== chaos: the full fault-tolerance suite, including the"
  echo "   SIGKILL/SIGTERM subprocess resume proofs"
  JAX_PLATFORMS=cpu python -m pytest tests/test_faults.py -q \
    -p no:cacheprovider
}

run_tier1() {
  echo "== tier1: mxlint (concurrency/invariant analyzer, subsumes the"
  echo "   old envdoc+faultdoc gates) + serving smoke + generation"
  echo "   smoke + resilience smoke + dist-resilience smoke + chaos"
  echo "   smoke + cache smoke + health smoke + bulking smoke +"
  echo "   input-pipeline smoke + dist-comm smoke + trace smoke +"
  echo "   bench regression check + the tier-1 pytest selection"
  run_mxlint
  run_serving_smoke
  run_generation_smoke
  run_resilience_smoke
  run_dist_resilience_smoke
  run_chaos_smoke
  run_cache_smoke
  run_health_smoke
  run_bulk_smoke
  run_input_pipeline_smoke
  run_dist_comm_smoke
  run_trace_smoke
  run_bench_check
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
}

run_unit() {
  echo "== unit: full Python suite (virtual CPU mesh)"
  python -m pytest tests/ -q --ignore=tests/test_distributed.py
}

run_dist() {
  echo "== dist: real multi-process launcher tests"
  python -m pytest tests/test_distributed.py -q
}

run_exec_cache() {
  echo "== exec-cache: core suite with the executable cache forced on"
  MXNET_IMPERATIVE_EXEC_CACHE=1 python -m pytest -q \
    tests/test_imperative_cache.py tests/test_autograd.py \
    tests/test_ndarray.py tests/test_gluon.py tests/test_numpy.py \
    tests/test_rnn.py tests/test_sparse.py
}

run_naive_engine() {
  echo "== naive-engine: synchronous dispatch mode"
  MXNET_ENGINE_TYPE=NaiveEngine python -m pytest -q \
    tests/test_autograd.py tests/test_ndarray.py tests/test_gluon.py
}

run_dryrun() {
  echo "== dryrun: multichip sharding + entry compile check"
  python __graft_entry__.py
}

run_tpu_sweep() {
  echo "== tpu-sweep: op sweep with default ctx = tpu"
  MXNET_TEST_CTX=tpu python -m pytest tests/test_op_sweep.py -q
}

run_tpu_core() {
  echo "== tpu-core: op sweep + core file sample with default ctx = tpu"
  echo "   (the tractable on-chip gate; tpu-unit is the exhaustive one)"
  MXNET_TEST_CTX=tpu python -m pytest -q tests/test_op_sweep.py \
    tests/test_autograd.py tests/test_gluon.py tests/test_optimizer.py \
    tests/test_ndarray.py tests/test_numpy.py tests/test_rnn.py \
    tests/test_misc.py tests/test_sparse.py tests/test_image.py \
    tests/test_amp.py
}

run_tpu_unit() {
  echo "== tpu-unit: the WHOLE suite with default ctx = tpu (the"
  echo "   reference's test_operator_gpu.py ctx-flip; host-only"
  echo "   multi-device tests auto-skip via tests/conftest.py)"
  MXNET_TEST_CTX=tpu python -m pytest tests/ -q
}

run_tpu_unit_batched() {
  # the same exhaustive gate run FILE BY FILE with an incremental
  # result log — survives tunnel hiccups with partial evidence and
  # yields the per-file pass counts PARITY records (r4: 725 green).
  # Per-file exit codes are the pass/fail signal (the summary-line grep
  # would miss collection errors, timeouts, and crashes), and a failing
  # file must NOT abort the loop (set -e would otherwise drop the
  # failing file's line and skip the rest — the opposite of
  # incremental evidence).
  echo "== tpu-unit-batched: whole suite on the chip, one file at a"
  echo "   time, incremental log in ci/tpu_unit_results.txt"
  : > ci/tpu_unit_results.txt
  bad=0
  for f in tests/test_*.py; do
    rc=0
    out=$(MXNET_TEST_CTX=tpu timeout 2400 python -m pytest "$f" -q \
          2>&1 | tail -1) || rc=$?
    if [ "$rc" -ne 0 ]; then
      bad=1
      out="$out [exit $rc]"
    fi
    echo "$f: $out" | tee -a ci/tpu_unit_results.txt
  done
  if [ "$bad" -ne 0 ]; then
    echo "tpu-unit-batched: FAILURES above" >&2
    exit 1
  fi
}

case "$variant" in
  native)       run_native ;;
  tier1)        run_tier1 ;;
  mxlint)       run_mxlint ;;
  envdoc)       run_envdoc ;;
  faultdoc)     run_faultdoc ;;
  serving-smoke) run_serving_smoke ;;
  generation-smoke) run_generation_smoke ;;
  resilience-smoke) run_resilience_smoke ;;
  dist-resilience-smoke) run_dist_resilience_smoke ;;
  chaos-smoke)  run_chaos_smoke ;;
  cache-smoke)  run_cache_smoke ;;
  health-smoke) run_health_smoke ;;
  input-pipeline-smoke) run_input_pipeline_smoke ;;
  dist-comm-smoke) run_dist_comm_smoke ;;
  trace-smoke)  run_trace_smoke ;;
  bench-check)  run_bench_check ;;
  chaos)        run_chaos ;;
  bulk-smoke)   run_bulk_smoke ;;
  bulk-off)     run_bulk_off ;;
  unit)         run_unit ;;
  dist)         run_dist ;;
  exec-cache)   run_exec_cache ;;
  naive-engine) run_naive_engine ;;
  dryrun)       run_dryrun ;;
  tpu-sweep)    run_tpu_sweep ;;
  tpu-core)     run_tpu_core ;;
  tpu-unit)     run_tpu_unit ;;
  tpu-unit-batched) run_tpu_unit_batched ;;
  all)
    run_native
    run_envdoc
    run_unit
    run_dist
    run_exec_cache
    run_naive_engine
    run_dryrun
    ;;
  *)
    echo "unknown variant: $variant" >&2
    exit 2
    ;;
esac
echo "CI variant '$variant' PASSED"
